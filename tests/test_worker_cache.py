"""WorkerCore program cache (r5): same-structure trainers share compiled
window programs; anything the structural key cannot fingerprint bypasses.

Motivation (PERF.md r5): the benchmark matrix's epochs-to-target loop
constructs a fresh trainer per 1-epoch round; each construction re-traced
and re-lowered every window program, which the CPU conv-unroll made ~90 s
per round on the 1-core sandbox. Programs depend only on model STRUCTURE +
optimizer spec + loss/metrics + flags, so they are shared process-wide.
"""

import jax
import numpy as np
import optax

from distkeras_tpu import SingleTrainer
from distkeras_tpu.data import loaders
from distkeras_tpu.data.transformers import MinMaxTransformer, OneHotTransformer
from distkeras_tpu.models import zoo
from distkeras_tpu.workers import _CORE_CACHE


def _small_ds(n=64):
    ds = loaders.synthetic_mnist(n=n, seed=0)
    ds = MinMaxTransformer(0, 1, o_min=0, o_max=255).transform(ds)
    return OneHotTransformer(10, output_col="label_onehot").transform(ds)


def _trainer(model, lr=0.05):
    return SingleTrainer(
        model, "sgd", "categorical_crossentropy", learning_rate=lr,
        batch_size=16, num_epoch=1, label_col="label_onehot", seed=0,
    )


def test_same_structure_shares_programs_but_not_model():
    m1 = zoo.mnist_mlp(hidden=16, seed=0)
    m2 = zoo.mnist_mlp(hidden=16, seed=1)  # same structure, different init
    c1 = _trainer(m1)._make_core()
    c2 = _trainer(m2)._make_core()
    assert c1.window is c2.window  # shared compiled program
    assert c1.model is m1 and c2.model is m2  # caller's weights, not donor's
    diff = sum(
        float(np.abs(np.asarray(a) - np.asarray(b)).sum())
        for a, b in zip(
            jax.tree.leaves(c1.model.params), jax.tree.leaves(c2.model.params)
        )
    )
    assert diff > 0  # different seeds -> different weights survived rebind


def test_different_spec_gets_different_programs():
    m1 = zoo.mnist_mlp(hidden=16, seed=0)
    m2 = zoo.mnist_mlp(hidden=16, seed=0)
    m3 = zoo.mnist_mlp(hidden=24, seed=0)
    base = _trainer(m1)._make_core()
    assert _trainer(m2, lr=0.01)._make_core().window is not base.window
    assert _trainer(m3)._make_core().window is not base.window


def test_custom_optax_object_bypasses_cache():
    m1 = zoo.mnist_mlp(hidden=16, seed=0)
    m2 = zoo.mnist_mlp(hidden=16, seed=0)
    c1 = _trainer(m1)._make_core()
    t = SingleTrainer(
        m2, optax.sgd(0.05), "categorical_crossentropy",
        batch_size=16, num_epoch=1, label_col="label_onehot", seed=0,
    )
    assert t._make_core().window is not c1.window


def test_cached_core_trains_from_fresh_params():
    """Round-style reuse: train once, rebuild a trainer on the RETURNED
    model — the cached core must continue from the trained weights (the
    r5 staleness hazard the rebound-model design exists to prevent)."""
    ds = _small_ds()
    m = zoo.mnist_mlp(hidden=16, seed=0)
    trained1 = _trainer(m).train(ds)
    w1 = trained1.get_weights()
    trained2 = _trainer(trained1).train(ds)  # cache hit; must start from w1
    w2 = trained2.get_weights()
    assert any(
        not np.allclose(a, b) for a, b in zip(w1, w2)
    ), "second round did not train"
    # a fresh same-seed model through the same two rounds lands the same
    # trajectory — i.e. round 2 really started from round 1's weights
    mb = zoo.mnist_mlp(hidden=16, seed=0)
    ref = _trainer(_trainer(mb).train(ds)).train(ds)
    for a, b in zip(w2, ref.get_weights()):
        np.testing.assert_allclose(a, b, atol=1e-6)


def test_eamsgd_momentum_optimizer_never_collides_with_plain_sgd():
    """EAMSGD swaps self.optimizer for Nesterov-momentum SGD AFTER the
    base ctor; a cache key built only from (worker_optimizer, lr) would
    hand its windows plain SGD — or hand plain-SGD trainers momentum
    (r5 review finding)."""
    from distkeras_tpu import EAMSGD

    plain = _trainer(zoo.mnist_mlp(hidden=16, seed=0), lr=0.02)._make_core()
    e = EAMSGD(
        zoo.mnist_mlp(hidden=16, seed=0), "sgd", "categorical_crossentropy",
        learning_rate=0.02, batch_size=16, num_epoch=1, num_workers=2,
        label_col="label_onehot", seed=0,
    )
    ecore = e._make_core()
    assert ecore.window is not plain.window
    assert ecore.optimizer is e.optimizer  # the momentum one, not plain


def test_lr_schedule_bypasses_cache():
    """self.learning_rate flattens a schedule to its step-0 float; keying
    the cache on it would collide two different schedules (or a schedule
    with a constant) that share a step-0 value (r5 review finding)."""
    sched = optax.linear_schedule(0.05, 0.001, 100)
    m1 = zoo.mnist_mlp(hidden=16, seed=0)
    m2 = zoo.mnist_mlp(hidden=16, seed=0)
    c_const = _trainer(m1, lr=0.05)._make_core()
    t_sched = SingleTrainer(
        m2, "sgd", "categorical_crossentropy", learning_rate=sched,
        batch_size=16, num_epoch=1, label_col="label_onehot", seed=0,
    )
    # step-0 flattening (to f32), the trap: the float alone cannot
    # distinguish this schedule from the 0.05 constant
    assert abs(t_sched.learning_rate - 0.05) < 1e-6
    assert t_sched._make_core().window is not c_const.window


def test_attached_attention_bypasses_cache():
    from distkeras_tpu.parallel.ring_attention import attach_blockwise_attention

    def make():
        return zoo.transformer_classifier(
            vocab_size=8, seq_len=16, d_model=16, num_heads=2, depth=1, seed=0
        )

    plain = make()
    c_plain = _trainer(plain)._make_core()
    hooked = make()
    assert attach_blockwise_attention(hooked, block_size=8) == 1
    c_hooked = _trainer(hooked)._make_core()
    assert c_hooked.window is not c_plain.window


def test_window_unroll_decision():
    """CPU backend: conv models unroll (33x while-loop pathology), dense
    models keep the loop (measured ~2x faster there) — PERF.md r5."""
    from distkeras_tpu.workers import _window_unroll

    assert _window_unroll(zoo.mnist_cnn(seed=0)) is True
    assert _window_unroll(zoo.resnet18(
        num_classes=10, input_shape=(32, 32, 3), seed=0)) is True  # nested convs
    assert _window_unroll(zoo.mnist_mlp(hidden=16, seed=0)) is False
    assert _window_unroll(zoo.transformer_classifier(
        vocab_size=8, seq_len=16, d_model=16, num_heads=2, depth=1, seed=0
    )) is False


def test_fused_layernorm_hook_bypasses_cache():
    """norm_fn is as trace-affecting and config-invisible as attention_fn
    (r5 review finding: the bypass must cover ALL runtime hooks)."""
    from distkeras_tpu.ops.fused_layernorm import attach_fused_layernorm

    def make():
        return zoo.transformer_classifier(
            vocab_size=8, seq_len=16, d_model=16, num_heads=2, depth=1, seed=0
        )

    c_plain = _trainer(make(), lr=0.027)._make_core()
    hooked = make()
    assert attach_fused_layernorm(hooked) > 0
    assert _trainer(hooked, lr=0.027)._make_core().window is not c_plain.window


def test_expert_mesh_hook_bypasses_cache():
    from jax.sharding import Mesh

    from distkeras_tpu.parallel.expert_parallel import attach_expert_mesh

    def make():
        return zoo.moe_transformer_classifier(
            vocab_size=8, seq_len=16, d_model=16, num_heads=2, depth=1,
            num_experts=2, seed=0,
        )

    c_plain = _trainer(make(), lr=0.029)._make_core()
    hooked = make()
    mesh = Mesh(np.array(jax.devices()[:2]), ("expert",))
    assert attach_expert_mesh(hooked, mesh) > 0
    assert _trainer(hooked, lr=0.029)._make_core().window is not c_plain.window


def test_shell_entry_does_not_pin_donor_params():
    """The cache entry must hold a params-stripped shell, and predict()'s
    memoized jitted lambda must not ride the shell back to the donor
    (r5 review finding: _predict_fn closes over the donor model)."""
    ds = _small_ds(n=32)
    m = zoo.mnist_mlp(hidden=16, seed=0)
    feats = np.asarray(ds["features"][:4], dtype=np.float32)
    m.predict(feats)  # memoizes _predict_fn on the model
    c = _trainer(m, lr=0.033)._make_core()
    entry = next(
        core for core in _CORE_CACHE.values() if core.window is c.window
    )
    assert entry.model.params is None and entry.model.state is None
    assert "_predict_fn" not in entry.model.__dict__


def test_donor_mutation_drops_cache_entry():
    """Attaching a hook to the DONOR model after caching must invalidate
    the entry: later same-config constructions rebuild instead of trusting
    programs whose future retraces would see the hooked apply."""
    from distkeras_tpu.parallel.ring_attention import attach_blockwise_attention

    def make():
        return zoo.transformer_classifier(
            vocab_size=8, seq_len=16, d_model=16, num_heads=2, depth=1, seed=3
        )

    donor = make()
    c1 = _trainer(donor, lr=0.031)._make_core()  # unique spec => fresh entry
    # the entry is the params-stripped shell core sharing c1's programs
    assert any(core.window is c1.window for core in _CORE_CACHE.values())
    attach_blockwise_attention(donor, block_size=8)
    c2 = _trainer(make(), lr=0.031)._make_core()
    assert c2.window is not c1.window
