"""Real multi-OS-process integration (VERDICT r2 missing #2): the DCN
topology executes across process boundaries, not just loopback-in-process.

Two separate Python processes are launched through
``job_deployment.Job.run_local`` (so the DKT_* env plumbing is the thing
under test), join a real ``jax.distributed`` coordination service on CPU,
run a cross-process collective, and then exercise the reference's
driver/worker split (SURVEY §5.8 TPU mapping): rank 0 hosts the
``SocketParameterServer``, rank 1 trains DOWNPOUR windows against it over
a real TCP socket via ``RemoteParameterServerClient``.
"""

import socket
import textwrap
from concurrent.futures import ThreadPoolExecutor

import pytest

from distkeras_tpu.job_deployment import Job

# 256 rows / batch 16 = 16 batches; communication_window 4 -> 4 commits
_EXPECT_COMMITS = 4

_SCRIPT = textwrap.dedent(
    """
    import os, sys, time
    os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=1"
    import jax
    jax.config.update("jax_platforms", "cpu")

    from distkeras_tpu.parallel import multihost

    assert multihost.initialize() is True, "DKT env plumbing failed"
    assert multihost.num_processes() == 2

    import numpy as np
    from jax.experimental import multihost_utils

    pid = multihost.process_id()
    ps_port = int(sys.argv[1])

    # cross-process collective: both ranks see both contributions
    g = multihost_utils.process_allgather(np.array([float(pid + 1)]))
    assert sorted(np.asarray(g).reshape(-1).tolist()) == [1.0, 2.0], g
    print("ALLGATHER_OK", flush=True)

    from distkeras_tpu.models import zoo
    from distkeras_tpu.ops.optimizers import get_optimizer
    from distkeras_tpu.parameter_servers import (
        DeltaParameterServer,
        RemoteParameterServerClient,
        SocketParameterServer,
    )
    from distkeras_tpu.workers import DOWNPOURWorker, WorkerCore

    model = zoo.mnist_mlp(hidden=8)
    EXPECT = {expect}

    if multihost.is_coordinator():
        init = [np.copy(x) for x in jax.tree.leaves(model.params)]
        ps = DeltaParameterServer(model.params)
        srv = SocketParameterServer(ps, port=ps_port)
        srv.start()
        deadline = time.time() + 180
        while ps.num_updates < EXPECT and time.time() < deadline:
            time.sleep(0.2)
        n = ps.num_updates
        final = jax.tree.leaves(ps.get_params())
        srv.stop()
        assert n == EXPECT, "expected {{}} commits, saw {{}}".format(EXPECT, n)
        assert any(
            not np.allclose(a, np.asarray(b)) for a, b in zip(init, final)
        ), "center never moved"
        print("PS_DONE", n, flush=True)
    else:
        from distkeras_tpu.data import loaders
        from distkeras_tpu.data.transformers import (
            MinMaxTransformer,
            OneHotTransformer,
        )

        ds = loaders.synthetic_mnist(n=256, seed=0)
        ds = MinMaxTransformer(0, 1, o_min=0, o_max=255).transform(ds)
        ds = OneHotTransformer(10, output_col="label_onehot").transform(ds)
        client = None
        for _ in range(300):  # the PS comes up when rank 0 gets there
            try:
                client = RemoteParameterServerClient("127.0.0.1", ps_port)
                break
            except (ConnectionError, OSError):
                time.sleep(0.2)
        assert client is not None, "PS never came up"
        core = WorkerCore(
            model, get_optimizer("sgd", 0.05), "categorical_crossentropy"
        )
        w = DOWNPOURWorker(core, client, 0, "features", "label_onehot", 4)
        w.train(ds, batch_size=16, num_epoch=1)
        client.close()
        assert w._seq == EXPECT, w._seq
        print("WORKER_DONE", w._seq, flush=True)
    print("MARKER_OK", flush=True)
    """
)


def _free_port() -> int:
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


# --- sync-DP psum across OS processes (VERDICT r3 task 6) ----------------
#
# The async/PS face of §5.8 is covered above; this is the OTHER face — the
# north-star path on a pod: ``SynchronousDistributedTrainer`` over a global
# 2-process mesh (1 CPU device per process), XLA inserting the gradient
# psum across the process boundary (Gloo collectives under CPU). Both
# ranks must agree with each other AND with the single-process trajectory.

_SYNC_SCRIPT = textwrap.dedent(
    """
    import os
    os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=1"
    import jax
    jax.config.update("jax_platforms", "cpu")

    from distkeras_tpu.parallel import multihost

    assert multihost.initialize() is True, "DKT env plumbing failed"
    assert multihost.num_processes() == 2
    assert len(jax.devices()) == 2 and len(jax.local_devices()) == 1

    import numpy as np
    from distkeras_tpu import (
        MinMaxTransformer,
        OneHotTransformer,
        SynchronousDistributedTrainer,
    )
    from distkeras_tpu.data import loaders
    from distkeras_tpu.models import zoo

    ds = loaders.synthetic_mnist(n=512, seed=0)
    ds = MinMaxTransformer(0, 1, o_min=0, o_max=255).transform(ds)
    ds = OneHotTransformer(10, output_col="label_onehot").transform(ds)
    t = SynchronousDistributedTrainer(
        zoo.mnist_mlp(seed=0), "sgd", "categorical_crossentropy",
        learning_rate=0.05, batch_size=32, num_epoch=2, num_workers=2,
        label_col="label_onehot", seed=0,
    )
    model = t.train(ds, shuffle=True)
    digest = float(sum(
        float(np.abs(np.asarray(x)).sum())
        for x in jax.tree.leaves(model.params)
    ))
    print("PARAM_DIGEST", repr(digest), flush=True)

    # ZeRO-1 across the process boundary: adam moments shard over the
    # 2-process mesh, the rebuild collectives ride the same Gloo
    # transport. Parity-pinned IN the multi-controller regime against a
    # replicated-state adam baseline with identical hyperparameters —
    # rank agreement alone would also pass for a deterministic-but-wrong
    # trajectory (r4 review finding).
    def adam_digest(shard):
        t = SynchronousDistributedTrainer(
            zoo.mnist_mlp(seed=0), "adam", "categorical_crossentropy",
            learning_rate=1e-3, batch_size=32, num_epoch=1, num_workers=2,
            shard_opt_state=shard, label_col="label_onehot", seed=0,
        )
        m = t.train(ds, shuffle=True)
        return float(sum(
            float(np.abs(np.asarray(x)).sum())
            for x in jax.tree.leaves(m.params)
        ))

    zdigest = adam_digest(True)
    assert np.isfinite(zdigest)
    base = adam_digest(False)
    assert np.isclose(zdigest, base, rtol=1e-4), (zdigest, base)
    print("ZERO_DIGEST", repr(zdigest), flush=True)
    print("SYNC2_OK", flush=True)
    """
)


def _single_process_sync_digest() -> float:
    """The same training run on the in-process 2-of-8-device mesh."""
    import jax
    import numpy as np

    from distkeras_tpu import (
        MinMaxTransformer,
        OneHotTransformer,
        SynchronousDistributedTrainer,
    )
    from distkeras_tpu.data import loaders
    from distkeras_tpu.models import zoo

    ds = loaders.synthetic_mnist(n=512, seed=0)
    ds = MinMaxTransformer(0, 1, o_min=0, o_max=255).transform(ds)
    ds = OneHotTransformer(10, output_col="label_onehot").transform(ds)
    t = SynchronousDistributedTrainer(
        zoo.mnist_mlp(seed=0), "sgd", "categorical_crossentropy",
        learning_rate=0.05, batch_size=32, num_epoch=2, num_workers=2,
        label_col="label_onehot", seed=0,
    )
    model = t.train(ds, shuffle=True)
    return float(sum(
        float(np.abs(np.asarray(x)).sum())
        for x in jax.tree.leaves(model.params)
    ))


@pytest.mark.slow
def test_two_process_sync_dp_matches_single_process(tmp_path):
    """SynchronousDistributedTrainer trains across 2 OS processes (psum
    over the process boundary) and lands the single-process trajectory."""
    script = tmp_path / "sync2proc.py"
    script.write_text(_SYNC_SCRIPT)
    coord_port = _free_port()
    job = Job(
        str(script),
        num_hosts=2,
        coordinator_address=f"localhost:{coord_port}",
    )
    with ThreadPoolExecutor(2) as ex:
        futs = [
            ex.submit(
                job.run_local,
                workdir=str(tmp_path / f"rank{i}"),
                process_id=i,
                timeout=300,
            )
            for i in range(2)
        ]
        rank0, rank1 = (f.result(timeout=360) for f in futs)

    assert rank0.returncode == 0, f"rank0:\n{rank0.stdout}\n{rank0.stderr}"
    assert rank1.returncode == 0, f"rank1:\n{rank1.stdout}\n{rank1.stderr}"
    digests, zdigests = [], []
    for proc in (rank0, rank1):
        assert "SYNC2_OK" in proc.stdout
        line = next(
            ln for ln in proc.stdout.splitlines()
            if ln.startswith("PARAM_DIGEST")
        )
        digests.append(float(line.split()[1]))
        zline = next(
            ln for ln in proc.stdout.splitlines()
            if ln.startswith("ZERO_DIGEST")
        )
        zdigests.append(float(zline.split()[1]))
    # both ranks computed the identical replicated result...
    assert digests[0] == digests[1], digests
    # ...including under ZeRO-1 sharded optimizer state
    assert zdigests[0] == zdigests[1], zdigests
    # ...and it matches the single-process trajectory (r4 calibration saw
    # exact equality; the tolerance absorbs reduction-order drift)
    import numpy as np

    np.testing.assert_allclose(
        digests[0], _single_process_sync_digest(), rtol=1e-5
    )


@pytest.mark.slow
def test_two_process_ps_training_over_real_sockets(tmp_path):
    script = tmp_path / "train2proc.py"
    script.write_text(_SCRIPT.format(expect=_EXPECT_COMMITS))
    coord_port, ps_port = _free_port(), _free_port()
    job = Job(
        str(script),
        num_hosts=2,
        coordinator_address=f"localhost:{coord_port}",
        script_args=[str(ps_port)],
    )
    with ThreadPoolExecutor(2) as ex:
        futs = [
            ex.submit(
                job.run_local,
                workdir=str(tmp_path / f"rank{i}"),
                process_id=i,
                timeout=300,
            )
            for i in range(2)
        ]
        rank0, rank1 = (f.result(timeout=360) for f in futs)

    assert rank0.returncode == 0, f"rank0:\n{rank0.stdout}\n{rank0.stderr}"
    assert rank1.returncode == 0, f"rank1:\n{rank1.stdout}\n{rank1.stderr}"
    for proc in (rank0, rank1):
        assert "ALLGATHER_OK" in proc.stdout
        assert "MARKER_OK" in proc.stdout
    assert f"PS_DONE {_EXPECT_COMMITS}" in rank0.stdout
    assert f"WORKER_DONE {_EXPECT_COMMITS}" in rank1.stdout
