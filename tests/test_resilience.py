"""Overload defense and gray-failure resilience (serving/resilience.py
and its wiring through client, router, and scheduler).

Four tiers:

- primitive units with injected clocks: retry budgets, circuit
  breakers, latency trackers, hedge-delay resolution, and the
  admission controller's CoDel latch + brownout ladder — no sleeps,
  no sockets;
- the full-jitter retry distribution pin: ``RetryPolicy.delay`` draws
  uniformly from ``[0, min(max_delay, base * 2^attempt)]`` and passes
  server ``retry_after`` hints through verbatim;
- scheduler/client integration: the shed gate refusing typed at the
  engine door, the client's budget refusing to amplify, client-side
  hedging winning on a stalled primary;
- router integration (FakeReplica fleets): fleet-side retry-budget
  enforcement, hedged routing pairing invariants, and the gray-failure
  chaos drill — a ``net.delay``-slowed but health-green replica trips
  its breaker open, routed latency recovers, and the breaker closes
  again after the seam disarms (marked ``chaos``).
"""

from __future__ import annotations

import os
import sys
import threading
import time

import numpy as np
import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, os.path.join(REPO, "tools"))

import loadgen  # noqa: E402

from distkeras_tpu.serving.resilience import (
    CLOSED,
    HALF_OPEN,
    OPEN,
    AdmissionController,
    CircuitBreaker,
    LatencyTracker,
    RetryBudget,
    as_breaker_config,
    as_retry_budget,
    as_shed_gate,
    resolve_hedge_delay,
)
from distkeras_tpu.serving.scheduler import (
    ContinuousBatcher,
    OverloadedError,
    ServeRequest,
    ShedError,
)
from test_fleet import FakeReplica, _client, _router
from test_serving import FakeStepper


class Tick:
    """Injected monotonic clock: advances only when told to."""

    def __init__(self, t=0.0):
        self.t = float(t)

    def __call__(self):
        return self.t

    def advance(self, dt):
        self.t += dt


def _req(plen=3, max_new=4, **kw):
    return ServeRequest(np.arange(1, plen + 1), max_new, **kw)


# ------------------------------------------------------ retry budget


def test_retry_budget_deposits_grants_and_exhausts():
    b = RetryBudget(ratio=0.25, burst=2.0)
    # starts full: a cold client may retry immediately
    assert b.acquire() and b.acquire()
    assert not b.acquire()  # dry
    assert b.exhausted == 1 and b.grants == 2
    # 4 original attempts deposit ratio*4 = 1.0 token
    for _ in range(4):
        b.note_attempt()
    assert b.acquire()
    assert not b.acquire()
    # deposits cap at burst, never bank unbounded credit
    for _ in range(1000):
        b.note_attempt()
    assert b.tokens == pytest.approx(2.0)
    snap = b.snapshot()
    assert snap["attempts"] == 1004 and snap["grants"] == 3
    assert snap["exhausted"] == 2


def test_retry_budget_spec_coercion():
    assert as_retry_budget(None) is None
    assert as_retry_budget(False) is None
    b = as_retry_budget(True)
    assert isinstance(b, RetryBudget) and b.ratio == 0.1
    b = as_retry_budget({"ratio": 0.5, "burst": 3.0})
    assert b.ratio == 0.5 and b.burst == 3.0
    inst = RetryBudget()
    assert as_retry_budget(inst) is inst
    with pytest.raises(TypeError):
        as_retry_budget("lots")


# --------------------------------------------------- circuit breaker


def test_breaker_error_rate_trip_probe_and_close():
    clk = Tick()
    br = CircuitBreaker(
        window=10.0, min_requests=4, failure_threshold=0.5,
        open_secs=5.0, clock=clk,
    )
    # below min_requests nothing trips, however bad the rate
    assert br.record_failure() is None
    assert br.record_failure() is None
    assert br.state == CLOSED
    br.record_success()
    assert br.record_failure() == (CLOSED, OPEN)  # 3/4 failed
    assert br.open_cause == "error_rate"
    # open: no probe before open_secs
    assert not br.probe_due()
    granted, change = br.try_probe()
    assert not granted and change is None
    clk.advance(5.1)
    assert br.probe_due()
    granted, change = br.try_probe()
    assert granted and change == (OPEN, HALF_OPEN)
    # one probe in flight at a time
    assert not br.probe_due()
    assert br.try_probe() == (False, None)
    assert br.record_probe(ok=True) == (HALF_OPEN, CLOSED)
    assert br.open_cause is None
    assert br.snapshot()["window_outcomes"] == 0  # clean slate


def test_breaker_probe_failure_reopens_with_fresh_timer():
    clk = Tick()
    br = CircuitBreaker(
        window=10.0, min_requests=2, failure_threshold=0.5,
        open_secs=5.0, clock=clk,
    )
    br.record_failure()
    assert br.record_failure() == (CLOSED, OPEN)
    clk.advance(5.1)
    granted, _ = br.try_probe()
    assert granted
    assert br.record_probe(ok=False) == (HALF_OPEN, OPEN)
    assert br.open_cause == "probe_failed"
    assert not br.probe_due()  # the open timer restarted
    clk.advance(5.1)
    assert br.probe_due()


def test_breaker_latency_outlier_streak_trips_and_resets():
    clk = Tick()
    br = CircuitBreaker(outlier_trips=3, open_secs=5.0, clock=clk)
    assert br.note_latency(True) is None
    assert br.note_latency(True) is None
    assert br.note_latency(False) is None  # streak reset
    assert br.state == CLOSED
    br.note_latency(True)
    br.note_latency(True)
    assert br.note_latency(True) == (CLOSED, OPEN)
    assert br.open_cause == "latency_outlier"
    # error-window outcomes never reached min_requests: the trip came
    # from the latency path alone (the gray-failure seam)
    assert br.snapshot()["state"] == OPEN


def test_breaker_config_coercion():
    assert as_breaker_config(None) is None
    assert as_breaker_config(False) is None
    assert as_breaker_config(True) == {}
    assert as_breaker_config({"window": 3.0}) == {"window": 3.0}
    with pytest.raises(TypeError):
        as_breaker_config(7)


# ------------------------------------- latency tracker + hedge delay


def test_latency_tracker_and_hedge_delay_resolution():
    t = LatencyTracker(capacity=16, min_samples=4)
    assert resolve_hedge_delay("p95", t) is None  # no evidence yet
    for v in (0.01, 0.02, 0.03, 0.04):
        t.note(v)
    assert len(t) == 4
    assert resolve_hedge_delay("p95", t) == pytest.approx(0.04)
    assert resolve_hedge_delay("p50", t) == pytest.approx(0.03)
    # numbers are used as-is; tracker state is irrelevant
    assert resolve_hedge_delay(0.25, None) == pytest.approx(0.25)
    assert resolve_hedge_delay(None, t) is None
    with pytest.raises(ValueError):
        resolve_hedge_delay("q95", t)
    with pytest.raises(ValueError):
        resolve_hedge_delay(-1.0, t)


# ------------------------------------------------ full-jitter retry


def test_retry_policy_full_jitter_distribution_and_hint():
    """Satellite pin: ``delay(attempt)`` is FULL jitter — uniform on
    ``[0, cap]`` with ``cap = min(max_delay, base * 2^attempt)`` — not
    equal-jitter, not decorrelated; and a server ``retry_after`` hint
    is honored verbatim (capped at max_delay), never jittered."""
    from distkeras_tpu.networking import RetryPolicy

    p = RetryPolicy(base_delay=0.1, max_delay=2.0, seed=7)
    for attempt in (0, 1, 3):
        cap = min(2.0, 0.1 * 2 ** attempt)
        draws = [p.delay(attempt) for _ in range(400)]
        assert all(0.0 <= d <= cap for d in draws)
        # the draws SPREAD over the interval: full jitter's signature
        # (a fixed or lower-bounded backoff would cluster high)
        assert min(draws) < 0.2 * cap
        assert max(draws) > 0.8 * cap
        mean = sum(draws) / len(draws)
        assert 0.35 * cap < mean < 0.65 * cap
    # hints ride verbatim — coordinated pacing from the server's own
    # estimate beats client-side guessing — but never past max_delay
    assert p.delay(0, hint=0.75) == pytest.approx(0.75)
    assert p.delay(5, hint=60.0) == pytest.approx(2.0)


# ------------------------------------------- admission controller


def test_admission_codel_latch_needs_sustained_excess():
    clk = Tick()
    g = AdmissionController(
        target_ms=50.0, interval_ms=500.0, clock=clk,
    )
    # a single spike above target does not latch
    g.note_delay(0.2)
    assert g.rung() == 0
    # sustained excess for >= interval does
    clk.advance(0.3)
    g.note_delay(0.2)
    assert g.rung() == 0
    clk.advance(0.3)
    g.note_delay(0.2)
    assert g.rung() == 1
    assert g.admit(0, 64) [0] == "shed"
    assert g.admit(1, 64)[0] == "admit"  # higher class rides through
    # one below-target sojourn releases the latch immediately
    g.note_delay(0.01)
    assert g.rung() == 0
    assert g.admit(0, 64)[0] == "admit"


def test_admission_latch_releases_on_stale_evidence():
    clk = Tick()
    g = AdmissionController(
        target_ms=50.0, interval_ms=500.0, clock=clk,
    )
    g.note_delay(0.2)
    clk.advance(0.6)
    g.note_delay(0.2)
    assert g.rung() == 1
    # no admissions at all for two intervals: queue is empty, not
    # congested — shedding on stale evidence would brown out idle
    clk.advance(1.1)
    assert g.rung() == 0


def test_admission_burn_ladder_clamp_and_refuse():
    clk = Tick()
    verdict = {"burn": "ok"}
    g = AdmissionController(
        target_ms=50.0, interval_ms=500.0, burn_fn=lambda: verdict,
        burn_interval=0.0, clamp_frac=0.25, clock=clk,
    )
    assert g.admit(0, 64) == ("admit", None, None)
    verdict = {"burn": "burning"}  # rung 1: shed lowest class
    act, hint, clamp = g.admit(0, 64)
    assert act == "shed" and hint >= 25.0 and clamp is None
    assert g.poll_transition() == (0, 1)
    assert g.poll_transition() is None  # once per transition
    verdict = {"burn": "spiking"}  # rung 2: clamp survivors
    act, hint, clamp = g.admit(3, 64)
    assert act == "admit" and clamp == 16
    verdict = {"burn": "breach"}  # rung 3: refuse everyone, typed
    act, hint, clamp = g.admit(9, 64)
    assert act == "refuse" and hint >= 25.0
    st = g.state()
    assert st["rung"] == 3 and st["burn_rung"] == 3
    # a crashing burn_fn is neutral, never an implicit brownout
    g2 = AdmissionController(
        burn_fn=lambda: 1 / 0, burn_interval=0.0, clock=clk,
    )
    assert g2.admit(0, 64)[0] == "admit"


def test_shed_gate_spec_coercion():
    assert as_shed_gate(None) is None
    assert as_shed_gate(False) is None
    assert isinstance(as_shed_gate(True), AdmissionController)
    g = as_shed_gate({"target_ms": 10.0}, burn_fn=len)
    assert g.target == pytest.approx(0.010) and g.burn_fn is len
    inst = AdmissionController()
    assert as_shed_gate(inst) is inst


# -------------------------------------------- scheduler integration


def test_batcher_shed_gate_refuses_typed_and_clamps():
    clk = Tick()
    verdict = {"burn": "ok"}
    gate = AdmissionController(
        target_ms=50.0, burn_fn=lambda: verdict, burn_interval=0.0,
        clamp_frac=0.25, clock=clk,
    )
    st = FakeStepper(num_slots=2)
    b = ContinuousBatcher(st, queue_capacity=8, shed_gate=gate)
    b.submit(_req(max_new=2))  # healthy: admitted untouched
    verdict = {"burn": "burning"}
    with pytest.raises(ShedError) as ei:
        b.submit(_req(max_new=2, priority=0))
    assert ei.value.code == "overloaded"
    assert ei.value.retry_after_ms >= 25.0
    r_hi = b.submit(_req(max_new=8, priority=2))  # class rides through
    assert r_hi.max_new_tokens == 8
    verdict = {"burn": "spiking"}
    r_cl = b.submit(_req(max_new=8, priority=2))
    assert r_cl.max_new_tokens == 2  # clamped, not refused
    verdict = {"burn": "breach"}
    with pytest.raises(ShedError):
        b.submit(_req(max_new=2, priority=9))
    s = b.stats()
    assert s["shed_overloaded"] == 2 and s["shed_clamped"] == 1


def test_batcher_shed_gate_sees_queue_sojourn():
    clk = Tick()
    gate = AdmissionController(target_ms=50.0, clock=clk)
    st = FakeStepper(num_slots=1)
    b = ContinuousBatcher(st, queue_capacity=8, shed_gate=gate)
    b.submit(_req(max_new=2))
    b.step()  # admits: sojourn ~0 -> below target, no latch
    assert gate.state()["sojourn_ms"] is not None
    assert not gate.state()["shedding"]


# ------------------------------------------------- loadgen storm


def test_loadgen_storm_three_phases():
    """The storm process: steady baseline, a 5x rectangular burst,
    recovery back to baseline — deterministic, and the phase summary
    documents the burst it will drive at the shed gate."""
    kw = dict(duration=9.0, seed=5, burst_start=3.0, burst_len=3.0,
              burst_factor=5.0)
    a = loadgen.arrivals("storm", 20.0, **kw)
    b = loadgen.arrivals("storm", 20.0, **kw)
    assert np.array_equal(a, b) and np.all(np.diff(a) >= 0)
    phase = lambda lo, hi: int(((a >= lo) & (a < hi)).sum())  # noqa: E731
    base, burst, rec = phase(0, 3), phase(3, 6), phase(6, 9)
    # the burst runs ~5x the baseline; recovery returns to it
    assert burst > 3 * base
    assert burst > 3 * rec
    with pytest.raises(ValueError):
        loadgen.arrivals("storm", 20.0, duration=9.0)  # needs bounds
    trace = loadgen.make_trace(
        process="storm", rate=20.0, tenants=loadgen.storm_tenants(64),
        **kw,
    )
    s = loadgen.summarize(trace, phases=3)
    assert s["phase_rates"][1]["rate"] > 2.5 * s["phase_rates"][0]["rate"]
    # the preset carries both QoS classes the brownout ladder splits
    prios = {t["priority"] for t in map(dict, loadgen.storm_tenants())}
    assert prios == {0, 2}
    assert {ev["tenant"] for ev in trace} == {"hi", "lo"}


def test_loadgen_summarize_outcomes_ledger():
    got = loadgen.summarize_outcomes(
        ["ok", "ok", "shed", "budget_refused", "error:unavailable"]
    )
    assert got == {
        "total": 5, "ok": 2, "shed": 1, "budget_refused": 1,
        "errors": {"unavailable": 1},
    }


# ----------------------------------------------- client integration


def test_client_retry_budget_stops_amplification():
    f = FakeReplica(1)
    try:
        f.overload_next = 100  # the replica sheds every generate
        from distkeras_tpu.serving import ServingClient
        from distkeras_tpu.networking import RetryPolicy

        cli = ServingClient(
            f.endpoint[0], f.endpoint[1], timeout=10.0,
            retry=RetryPolicy(
                max_attempts=8, base_delay=0.001, max_delay=0.005,
            ),
            retry_budget={"ratio": 0.0, "burst": 2.0},
        )
        with cli:
            with pytest.raises(OverloadedError):
                cli.generate(np.arange(4, dtype=np.int32), 2)
            # 1 original + exactly 2 budget-granted retries hit the
            # wire; the 4th attempt was refused LOCALLY and the typed
            # error surfaced unamplified
            assert cli.retries == 2
            assert cli.budget_refused == 1
        assert f.calls.count("generate") == 3
    finally:
        f.kill()


def test_client_hedge_wins_on_stalled_primary():
    class Stall:
        """First generate stalls 0.6 s; later ones answer at once —
        the hedged sibling connection beats the stalled primary."""

        def __init__(self):
            self.n = 0
            self.lock = threading.Lock()

        def wait(self, timeout=None):
            with self.lock:
                self.n += 1
                first = self.n == 1
            if first:
                time.sleep(0.6)

    f = FakeReplica(3)
    try:
        f.block = Stall()
        from distkeras_tpu.serving import ServingClient

        with ServingClient(
            f.endpoint[0], f.endpoint[1], timeout=10.0,
            hedge_after=0.1,
        ) as cli:
            t0 = time.monotonic()
            out = cli.generate(np.arange(4, dtype=np.int32), 3)
            dt = time.monotonic() - t0
            assert out[-3:].tolist() == [3, 3, 3]
            assert dt < 0.55  # did not wait out the stall
            assert cli.hedges_launched == 1
            assert cli.hedge_wins == 1
            # a fast reply later hedges nothing
            out2 = cli.generate(np.arange(5, dtype=np.int32), 3)
            assert out2[-3:].tolist() == [3, 3, 3]
            assert cli.hedges_launched == 1
    finally:
        f.kill()


def test_client_hedge_spec_validated_eagerly():
    from distkeras_tpu.serving import ServingClient

    with pytest.raises(ValueError):
        ServingClient("127.0.0.1", 1, hedge_after="q95")


# ----------------------------------------------- router integration


def test_router_retry_budget_refuses_marked_retries():
    f = FakeReplica(1)
    router = None
    try:
        f.overload_next = 100
        router = _router(f, retry_budget={"ratio": 0.0, "burst": 1.0})
        from distkeras_tpu.networking import RetryPolicy

        with _client(
            router,
            retry=RetryPolicy(
                max_attempts=6, base_delay=0.001, max_delay=0.005,
            ),
        ) as cli:
            with pytest.raises(OverloadedError):
                cli.generate(np.arange(4, dtype=np.int32), 2)
        # original + ONE granted retry reached the replica; every
        # further retry died at the router's budget, typed, without
        # touching a replica (the no-amplification contract)
        assert f.calls.count("generate") == 2
        assert router.retry_budget_exhausted.value >= 1
    finally:
        if router is not None:
            router.shutdown()
        f.kill()


def test_router_hedge_pairing_and_first_win():
    f1, f2 = FakeReplica(1), FakeReplica(2)
    router = None
    try:
        router = _router(f1, f2, affinity=False, hedge_after=0.15)
        with _client(router) as cli:
            cli.generate(np.arange(4, dtype=np.int32), 2)  # warm
            f1.block = threading.Event()  # stall ONLY f1
            t0 = time.monotonic()
            outs = [
                cli.generate(np.arange(5 + i, dtype=np.int32), 2)
                for i in range(4)
            ]
            dt = time.monotonic() - t0
        assert all(o[-1] in (1, 2) for o in outs)
        assert dt < 4.0  # no request waited out a 30 s stall
        c = router.counters
        assert c["hedges_launched"] >= 1
        f1.block.set()
        time.sleep(0.3)
        assert c["hedges_launched"] == c["hedge_wins"] + c["hedge_losers"]
        assert c["breaker_bypass_forwards"] == 0
    finally:
        if router is not None:
            router.shutdown()
        f1.kill()
        f2.kill()


@pytest.mark.chaos
def test_gray_failure_breaker_opens_recovers_and_closes(lm):
    """ACCEPTANCE (gray failure): one replica of a REAL 2-engine fleet
    is slowed via the ``net.delay`` seam — health polls stay green the
    whole time, so ejection never fires — and the router's breaker (a)
    opens on the latency-outlier path, (b) steers traffic off it so
    routed latency recovers, then (c) half-opens and closes after the
    seam disarms. Zero untyped errors anywhere."""
    from distkeras_tpu import faults
    from distkeras_tpu.serving import ServingEngine, ServingServer

    engines, servers = [], []
    router = None
    plan = faults.FaultPlan()
    try:
        for _ in range(2):
            eng = ServingEngine(lm, num_slots=2, queue_capacity=16).start()
            srv = ServingServer(eng).start()
            engines.append(eng)
            servers.append(srv)
        slow_port = int(servers[0].port)
        plan.arm(
            "net.delay", action="delay", delay=0.35, times=None,
            when=lambda ctx: ctx.get("port") == slow_port,
        ).activate()
        from distkeras_tpu.serving.fleet import FleetRouter

        router = FleetRouter(
            endpoints=[(s.host, s.port) for s in servers],
            health_interval=0.25, affinity=False,
            breaker=dict(
                open_secs=1.0, outlier_trips=2,
                outlier_factor=3.0, min_latency=0.050,
            ),
        ).start()
        slow_ep = (servers[0].host, int(servers[0].port))

        def breaker_state():
            for r in router.replicas():
                if tuple(r["endpoint"]) == slow_ep:
                    return r["breaker"]["state"], r["state"]
            return None, None

        prompt = np.arange(6, dtype=np.int32) % 11

        def burst(base, n=4):
            """n CONCURRENT generates — while the slow replica stalls
            one, the others land on the fast sibling, so BOTH build
            windowed latency (a serial driver would pile onto one)."""
            errs = []

            def one(i):
                try:
                    with _client(router) as c:
                        c.generate((prompt + base + i) % 11, 3)
                except Exception as e:  # noqa: BLE001
                    errs.append(e)

            ths = [
                threading.Thread(target=one, args=(i,)) for i in range(n)
            ]
            for t in ths:
                t.start()
            for t in ths:
                t.join(timeout=60)
            assert not errs, errs

        with _client(router) as cli:
            # drive traffic until the breaker opens (the per-replica
            # windows need history snapshots, which land on the health
            # loop's 1 s cadence)
            deadline = time.monotonic() + 60.0
            opened = False
            while time.monotonic() < deadline:
                burst(int(time.monotonic() * 10) % 40)
                bstate, rstate = breaker_state()
                assert rstate == "active"  # health-green while slow
                if bstate == "open":
                    opened = True
                    break
            assert opened, "breaker never opened on the slow replica"
            assert router.counters["breaker_opens"] >= 1
            # recovery: with the breaker open every request routes to
            # the healthy sibling — no 0.35 s stalls
            lats = []
            for i in range(6):
                t0 = time.monotonic()
                cli.generate((prompt + 50 + i) % 11, 3)
                lats.append(time.monotonic() - t0)
            assert max(lats) < 0.3, lats
            # disarm: the half-open probe finds a fast replica again
            plan.deactivate()
            deadline = time.monotonic() + 30.0
            closed = False
            while time.monotonic() < deadline:
                for i in range(3):
                    cli.generate((prompt + 100 + i) % 11, 3)
                if breaker_state()[0] == "closed":
                    closed = True
                    break
                time.sleep(0.1)
            assert closed, "breaker never closed after disarm"
            assert router.counters["breaker_closes"] >= 1
            assert router.counters["breaker_probes"] >= 1
        assert router.counters["breaker_bypass_forwards"] == 0
    finally:
        plan.deactivate()
        if router is not None:
            router.shutdown()
        for s in servers:
            s.shutdown()
        for e in engines:
            e.stop()


@pytest.fixture(scope="module")
def lm():
    from distkeras_tpu.models import zoo

    return zoo.transformer_lm(
        vocab_size=61, seq_len=32, d_model=32, num_heads=2, depth=2,
        seed=0,
    )


def test_dkt_top_renders_breaker_and_shed_columns():
    sys.path.insert(0, os.path.join(REPO, "tools"))
    from dkt_top import format_table

    samples = [
        {"name": "fleet_router_breaker_open_replicas", "kind": "gauge",
         "value": 1, "labels": {"replica": "router"}},
        {"name": "fleet_router_breaker_opens", "kind": "counter",
         "value": 2, "labels": {"replica": "router"}},
        {"name": "fleet_router_breaker_closes", "kind": "counter",
         "value": 1, "labels": {"replica": "router"}},
        {"name": "serving_shed_rung", "kind": "gauge", "value": 2,
         "labels": {"replica": "127.0.0.1:9001"}},
    ]
    out = format_table(samples)
    assert "== router  breakers=OPEN:1 ↑2↓1 " in out
    assert "== 127.0.0.1:9001  shed=clamp " in out
    # healthy router reads ok; columns absent when gauges absent
    ok = format_table(
        [{"name": "fleet_router_breaker_open_replicas", "kind": "gauge",
          "value": 0, "labels": {"replica": "router"}}]
    )
    assert "breakers=ok" in ok
    bare = format_table(
        [{"name": "fleet_router_forwards", "kind": "counter",
          "value": 3, "labels": {"replica": "router"}}]
    )
    assert "breakers" not in bare and "shed=" not in bare
