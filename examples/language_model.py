"""Causal language-model training — the autoregressive long-context family.

No reference counterpart (the reference's workloads are MLP/CNN/tabular —
SURVEY §5.7); this example drives ``zoo.transformer_lm`` through the normal
trainer surface: next-token loss with shift-by-one targets, per-window
next-token accuracy, optional sequence parallelism (the causal ppermute
ring shards the token axis), and a greedy-decode demo at the end.

The toy corpus is a "successor language" (token t+1 = token t + 1 mod V)
so learning is verifiable at a glance: the decode must count upward.

Usage:
    python examples/language_model.py [--seq 128] [--cpu]
    XLA_FLAGS=--xla_force_host_platform_device_count=8 \
        python examples/language_model.py --cpu --seq-parallel 8
"""

from __future__ import annotations

import argparse
import sys
import time

import numpy as np

sys.path.insert(0, ".")


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--seq", type=int, default=128)
    ap.add_argument("--vocab", type=int, default=32)
    ap.add_argument("--d-model", type=int, default=64)
    ap.add_argument("--heads", type=int, default=4)
    ap.add_argument("--depth", type=int, default=2)
    ap.add_argument("--batch", type=int, default=32)
    ap.add_argument("--rows", type=int, default=1024)
    ap.add_argument("--epochs", type=int, default=4)
    ap.add_argument("--seq-parallel", type=int, default=0,
                    help="shard the token axis this many ways through the "
                         "causal ring (0 = single-device dense attention)")
    ap.add_argument("--remat", action="store_true",
                    help="per-block jax.checkpoint: activation memory O(1) "
                         "in depth at ~1/3 extra FLOPs")
    ap.add_argument("--text", metavar="PATH", nargs="?", const="", default=None,
                    help="train on a real text file, byte-level (default: "
                         "the repository's LICENSE) instead of the toy "
                         "successor corpus")
    ap.add_argument("--int8", action="store_true",
                    help="serve the decode demo from an int8 weight-only "
                    "copy (ops.quantization.quantize_model) — quarter the "
                    "HBM weight bytes per token on chip")
    ap.add_argument("--save-bundle", metavar="PATH", default=None,
                    help="with --int8: persist the quantized serving copy "
                    "as a serving bundle, reload it, and run the decode "
                    "demo from the RELOADED model (what a serving host "
                    "does at boot)")
    ap.add_argument("--speculative", action="store_true",
                    help="also train a small draft LM and run the decode "
                    "demo speculatively (draft-and-verify; output is "
                    "exactly the main model's greedy decode) — prints "
                    "the measured acceptance per verify round")
    ap.add_argument("--cpu", action="store_true")
    args = ap.parse_args()
    if args.save_bundle and not args.int8:
        # fail BEFORE training, not after a long run
        ap.error("--save-bundle stores a QUANTIZED serving copy; "
                 "pass --int8 too")
    if args.speculative and (args.text is not None or args.seq < 8):
        ap.error("--speculative runs on the toy successor corpus with "
                 "--seq >= 8 (the draft needs the same cheap task)")
    # draft shape, valid by construction (heads must divide d_model):
    draft_heads = 2
    draft_d = max(16, args.d_model // 4)
    draft_d += draft_d % draft_heads
    from distkeras_tpu.parallel.backend import setup_backend

    # probe out-of-process: a dead TPU tunnel degrades to the virtual CPU
    # mesh instead of hanging in-process backend init (--cpu forces it)
    setup_backend(cpu=args.cpu, cpu_devices=8, fallback_cpu_devices=8)

    from distkeras_tpu import SequenceParallelTrainer, SingleTrainer
    from distkeras_tpu.data.dataset import Dataset
    from distkeras_tpu.models import zoo

    if args.text is not None:
        from distkeras_tpu.data import loaders

        ds = loaders.text_corpus(args.text or None, seq_len=args.seq)
        if args.vocab != 32 or args.rows != 1024:
            print("note: --text is byte-level; --vocab is forced to 256 and "
                  "--rows to the corpus window count")
        args.vocab = 256
        print(f"byte-level corpus: {len(ds)} windows of {args.seq}")
    else:
        rng = np.random.default_rng(0)
        starts = rng.integers(0, args.vocab, args.rows)
        xs = ((starts[:, None] + np.arange(args.seq)[None, :]) % args.vocab
              ).astype(np.int32)
        ds = Dataset({"features": xs, "label": xs})

    model = zoo.transformer_lm(
        vocab_size=args.vocab, seq_len=args.seq, d_model=args.d_model,
        num_heads=args.heads, depth=args.depth, seed=0, remat=args.remat,
    )
    kw = dict(
        loss="next_token_crossentropy",
        learning_rate=2e-3,
        batch_size=args.batch,
        num_epoch=args.epochs,
        metrics=["next_token_accuracy"],
        seed=0,
    )
    if args.seq_parallel:
        trainer = SequenceParallelTrainer(
            model, "adam", num_workers=args.seq_parallel, **kw
        )
    else:
        trainer = SingleTrainer(model, "adam", **kw)

    t0 = time.time()
    trained = trainer.train(ds)
    dt = time.time() - t0
    hist = [h for h in trainer.get_history() if "next_token_accuracy" in h]
    print(f"trained {len(ds)} rows x {args.epochs} epochs in {dt:.1f}s; "
          f"next-token accuracy {float(hist[0]['next_token_accuracy']):.3f} "
          f"-> {float(hist[-1]['next_token_accuracy']):.3f}")

    from distkeras_tpu.predictors import CachedSequenceGenerator

    serve_model = trained
    if args.int8:
        from distkeras_tpu.ops.quantization import count_quantized, quantize_model

        serve_model = quantize_model(trained.copy())
        print(f"serving int8 weight-only "
              f"({count_quantized(serve_model.params)} quantized matrices)")
        if args.save_bundle:
            import os

            from distkeras_tpu.utils.serialization import (
                load_serving_bundle,
                save_serving_bundle,
            )

            save_serving_bundle(args.save_bundle, serve_model)
            serve_model = load_serving_bundle(args.save_bundle)
            print(f"serving bundle: {os.path.getsize(args.save_bundle)} "
                  f"bytes at {args.save_bundle}; decoding from the "
                  f"RELOADED copy")
    gen = CachedSequenceGenerator(serve_model)
    if args.text is not None:
        p_len = min(16, max(1, args.seq // 2))
        prompt = ds["features"][len(ds) // 2 : len(ds) // 2 + 1, :p_len]
        steps = max(1, min(48, args.seq - p_len))
        out = gen.generate(prompt, steps=steps)
        txt = bytes(out[0].tolist()).decode("latin-1")
        print(f"decode from {txt[:p_len]!r} -> {txt[p_len:]!r}")
    elif args.seq >= 8:
        # a RAGGED serving batch: three prompts of different lengths in
        # one compiled scan, each continued `steps` tokens (the model
        # learned "count upward", so every row must keep counting from
        # its own prompt end); prompt tokens wrap into the vocab
        steps = min(12, args.seq - 5)
        v = args.vocab
        prompts = [
            np.array([3 % v], np.int32),
            np.array([x % v for x in (10, 11, 12)], np.int32),
            np.arange(5, dtype=np.int32) % v,
        ]
        outs = gen.generate(prompts, steps=steps)
        for row in outs:
            print("greedy decode:", row.tolist())
    else:
        # tiny --seq: the single-prompt demo still fits
        steps = min(12, args.seq - 1)
        out = gen.generate(np.array([[3 % args.vocab]], np.int32),
                           steps=steps)
        print("greedy decode:", out[0].tolist())

    if args.speculative:
        # train a much smaller draft on the same corpus and decode
        # draft-and-verify: the output must equal the main model's
        # greedy decode token for token; acceptance per verify round is
        # the quantity speculative serving lives on
        from distkeras_tpu.predictors import SpeculativeGenerator

        draft = zoo.transformer_lm(
            vocab_size=args.vocab, seq_len=args.seq, d_model=draft_d,
            num_heads=draft_heads, depth=1, seed=1,
        )
        draft_t = SingleTrainer(draft, "adam", **kw).train(ds)
        spec = SpeculativeGenerator(trained, draft_t, k=4)
        sp_steps = min(12, args.seq - 5)
        prompt = np.array([[3 % args.vocab]], np.int32)
        out_s = spec.generate(prompt, steps=sp_steps)
        # re-derive the greedy reference directly in BOTH modes: the
        # ragged demo above may have served the quantized copy (--int8),
        # and reading its outs[0] would couple this branch to the demo
        # branch having run at all
        plain = CachedSequenceGenerator(trained).generate(
            prompt, steps=sp_steps
        )[0]
        match = "EXACT" if (out_s[0] == plain).all() else "MISMATCH"
        print(f"speculative decode ({match} vs greedy): "
              f"{out_s[0].tolist()}; "
              f"{sp_steps} tokens in {int(spec.last_rounds[0])} verify "
              f"rounds ({sp_steps / int(spec.last_rounds[0]):.2f} "
              f"accepted/round)")


if __name__ == "__main__":
    main()
