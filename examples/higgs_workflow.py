"""ATLAS-Higgs tabular workflow — AEASGD (reference: examples/workflow.ipynb;
BASELINE config 3).

Pipeline: load CSV of physics features -> standard-scale -> one-hot ->
AEASGD trainer (elastic averaging) -> predictor -> evaluator.
"""

from __future__ import annotations

import argparse
import sys
import time

sys.path.insert(0, ".")

from distkeras_tpu import (
    AEASGD,
    AccuracyEvaluator,
    LabelIndexTransformer,
    ModelPredictor,
    OneHotTransformer,
)
from distkeras_tpu.data.loaders import load_csv, synthetic_higgs
from distkeras_tpu.data.transformers import StandardScaleTransformer
from distkeras_tpu.models.zoo import higgs_mlp


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--csv", default=None, help="Higgs CSV (label + features)")
    ap.add_argument("--epochs", type=int, default=3)
    ap.add_argument("--batch", type=int, default=64)
    ap.add_argument("--workers", type=int, default=4)
    ap.add_argument("--rho", type=float, default=5.0)
    ap.add_argument("--n", type=int, default=32768)
    ap.add_argument("--cpu", action="store_true",
                    help="force the CPU backend (virtual multi-device mesh "
                         "via XLA_FLAGS=--xla_force_host_platform_device_count=N)")
    args = ap.parse_args()
    from distkeras_tpu.parallel.backend import setup_backend

    # probe out-of-process: a dead TPU tunnel degrades to CPU instead of
    # hanging in-process backend init (--cpu forces it)
    setup_backend(cpu=args.cpu, cpu_devices=max(args.workers, 8),
                  fallback_cpu_devices=max(args.workers, 8))

    raw = load_csv(args.csv) if args.csv else synthetic_higgs(n=args.n)
    num_features = raw["features"].shape[1]
    ds = StandardScaleTransformer()(raw)
    ds = OneHotTransformer(2, input_col="label", output_col="label_onehot")(ds)
    train, test = ds.split(0.85, seed=7)

    model = higgs_mlp(num_features=num_features, seed=0)
    trainer = AEASGD(
        model, worker_optimizer="sgd", loss="categorical_crossentropy",
        learning_rate=0.05, label_col="label_onehot", batch_size=args.batch,
        num_epoch=args.epochs, num_workers=args.workers, rho=args.rho,
        communication_window=8,
    )
    t0 = time.time()
    trained = trainer.train(train, shuffle=True)
    print(f"trained in {time.time() - t0:.1f}s; "
          f"PS updates: {trainer.parameter_server.num_updates}")

    pred = ModelPredictor(trained).predict(test)
    pred = LabelIndexTransformer(2)(pred)
    acc = AccuracyEvaluator(
        prediction_col="prediction_index", label_col="label"
    ).evaluate(pred)
    print(f"test accuracy: {acc:.4f}")


if __name__ == "__main__":
    main()
