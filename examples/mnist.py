"""MNIST end-to-end — the canonical example (reference: examples/mnist.py).

Pipeline shape mirrors the reference exactly: load CSV -> transformers
(MinMax pixel scaling, one-hot labels, reshape for the CNN) -> trainer ->
predictor -> evaluator. BASELINE configs 1 (SingleTrainer, MLP) and
2 (DOWNPOUR, CNN, 8 workers).

Usage:
    python examples/mnist.py [single|downpour|sync] [--csv path/to/mnist.csv]
"""

from __future__ import annotations

import argparse
import sys
import time

sys.path.insert(0, ".")

from distkeras_tpu import (
    DOWNPOUR,
    AccuracyEvaluator,
    LabelIndexTransformer,
    MinMaxTransformer,
    ModelPredictor,
    OneHotTransformer,
    SingleTrainer,
    SynchronousDistributedTrainer,
)
from distkeras_tpu.data.loaders import mnist
from distkeras_tpu.data.transformers import ReshapeTransformer
from distkeras_tpu.models.zoo import mnist_cnn, mnist_mlp


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("mode", nargs="?", default="single",
                    choices=["single", "downpour", "sync"])
    ap.add_argument("--csv", default=None, help="MNIST CSV (label + 784 pixels)")
    ap.add_argument("--epochs", type=int, default=2)
    ap.add_argument("--batch", type=int, default=64)
    ap.add_argument("--workers", type=int, default=8)
    ap.add_argument("--zero", action="store_true",
                    help="sync mode: ZeRO-1 — shard optimizer moments "
                    "over the data axis (~workers-fold less per-device "
                    "optimizer memory, same trajectory)")
    ap.add_argument("--n", type=int, default=16384, help="synthetic rows if no CSV")
    ap.add_argument("--cpu", action="store_true",
                    help="force the CPU backend (virtual multi-device mesh "
                         "via XLA_FLAGS=--xla_force_host_platform_device_count=N)")
    args = ap.parse_args()
    from distkeras_tpu.parallel.backend import setup_backend

    # probe out-of-process: a dead TPU tunnel degrades to the virtual CPU
    # mesh instead of hanging in-process backend init (--cpu forces it)
    setup_backend(cpu=args.cpu, cpu_devices=max(args.workers, 8),
                  fallback_cpu_devices=max(args.workers, 8))

    # -- data pipeline (reference: examples/mnist.py transformer chain) ------
    raw = mnist(path=args.csv, n=args.n, flat=True)
    ds = MinMaxTransformer(n_min=0.0, n_max=1.0, o_min=0.0, o_max=255.0)(raw)
    ds = OneHotTransformer(10, input_col="label", output_col="label_onehot")(ds)
    train, test = ds.split(0.9, seed=7)

    if args.mode == "single":
        model = mnist_mlp(seed=0)
        trainer = SingleTrainer(
            model, worker_optimizer="adam", loss="categorical_crossentropy",
            label_col="label_onehot", batch_size=args.batch,
            num_epoch=args.epochs,
        )
    else:
        # CNN path: reshape flat pixels to (28, 28, 1)
        train = ReshapeTransformer("features", "features", (28, 28, 1))(train)
        test = ReshapeTransformer("features", "features", (28, 28, 1))(test)
        model = mnist_cnn(seed=0)
        cls = DOWNPOUR if args.mode == "downpour" else SynchronousDistributedTrainer
        # DOWNPOUR: N workers' window deltas sum at the PS -> local adam lr
        # scales by 1/N (benchmarks.py config-2 calibration); the sync
        # trainer means the global-batch loss, so full lr is right there
        lr = 1e-3 / args.workers if cls is DOWNPOUR else 1e-3
        extra = (
            {"shard_opt_state": True}
            if args.zero and cls is SynchronousDistributedTrainer
            else {}
        )
        trainer = cls(
            model, worker_optimizer="adam", learning_rate=lr,
            loss="categorical_crossentropy",
            label_col="label_onehot", batch_size=args.batch,
            num_epoch=args.epochs, num_workers=args.workers, **extra,
        )

    t0 = time.time()
    trained = trainer.train(train, shuffle=True)
    print(f"trained in {time.time() - t0:.1f}s "
          f"({len(train) * args.epochs / (time.time() - t0):.0f} samples/s)")

    # -- inference + evaluation (reference: ModelPredictor -> AccuracyEvaluator)
    pred = ModelPredictor(trained, features_col="features").predict(test)
    pred = LabelIndexTransformer(10)(pred)
    acc = AccuracyEvaluator(
        prediction_col="prediction_index", label_col="label"
    ).evaluate(pred)
    print(f"test accuracy: {acc:.4f}")


if __name__ == "__main__":
    main()
