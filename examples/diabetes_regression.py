"""REAL-data regression end-to-end — the regression face of the
reference's arbitrary-model support (reference: distkeras/trainers.py
trains whatever compiled Keras model the user hands it, regressors
included; SURVEY §3.1 Trainer contract).

Pipeline shape mirrors the classification examples: load the in-repo
442-row diabetes CSV (native C++ parser, float target) -> standardize
features AND target on train statistics only (leak-free) -> trainer
(``loss="mse"``) -> predictor -> R² evaluator. R² is scale-invariant, so
standardizing the target changes nothing about the reported number.

Usage:
    python examples/diabetes_regression.py [single|sync] [--cpu]
"""

from __future__ import annotations

import argparse
import sys
import time

sys.path.insert(0, ".")

from distkeras_tpu import (
    ModelPredictor,
    RSquaredEvaluator,
    SingleTrainer,
    StandardScaleTransformer,
    SynchronousDistributedTrainer,
)
from distkeras_tpu.data.loaders import diabetes
from distkeras_tpu.models.zoo import tabular_regressor


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("mode", nargs="?", default="single",
                    choices=["single", "sync"])
    ap.add_argument("--epochs", type=int, default=40)
    ap.add_argument("--batch", type=int, default=32)
    ap.add_argument("--workers", type=int, default=4)
    ap.add_argument("--cpu", action="store_true",
                    help="force the CPU backend (virtual multi-device mesh)")
    args = ap.parse_args()
    from distkeras_tpu.parallel.backend import setup_backend

    # probe out-of-process: a dead TPU tunnel degrades to the virtual CPU
    # mesh instead of hanging in-process backend init (--cpu forces it)
    setup_backend(cpu=args.cpu, cpu_devices=max(args.workers, 8),
                  fallback_cpu_devices=max(args.workers, 8))

    train, test = diabetes().split(0.85, seed=7)
    print(f"real diabetes: {len(train)} train rows, {len(test)} test rows")
    feats = StandardScaleTransformer().fit(train)
    target = StandardScaleTransformer(input_col="label").fit(train)
    train, test = (target.transform(feats.transform(d))
                   for d in (train, test))

    if args.mode == "single":
        trainer = SingleTrainer(
            tabular_regressor(seed=0), "adam", "mse",
            learning_rate=1e-3, batch_size=args.batch,
            num_epoch=args.epochs, seed=0,
        )
    else:
        trainer = SynchronousDistributedTrainer(
            tabular_regressor(seed=0), "adam", "mse",
            learning_rate=1e-3,
            batch_size=max(args.batch // args.workers, 1),
            num_workers=args.workers, num_epoch=args.epochs, seed=0,
        )

    t0 = time.perf_counter()
    trained = trainer.train(train, shuffle=True)
    dt = time.perf_counter() - t0

    pred = ModelPredictor(trained, batch_size=256).predict(test)
    r2 = RSquaredEvaluator().evaluate(pred)
    print(f"{args.mode}: {dt:.1f}s, REAL holdout R^2 {r2:.4f} "
          "(predict-the-mean baseline scores 0.0)")


if __name__ == "__main__":
    main()
