"""Per-optimizer accuracy-vs-time comparison — the reference README's
signature experiment (reference: README experiment plots + examples/*.ipynb
per-optimizer notebooks, SURVEY §3.2/§6): train the same model on the same
data under every distributed optimization scheme and compare wall-clock
time against reached accuracy.

Trainers covered: SingleTrainer (baseline), SynchronousDistributedTrainer
(psum allreduce), DOWNPOUR, AEASGD, EAMSGD, ADAG, DynSGD (async PS zoo).

Writes ``examples/experiments/optimizer_comparison.json`` (full curves) and
``.md`` (summary table). Usage:

    python examples/optimizer_comparison.py [--n 8192] [--rounds 5]
        [--workers 4] [--cpu]
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

sys.path.insert(0, ".")

from distkeras_tpu import (
    ADAG,
    AEASGD,
    DOWNPOUR,
    DynSGD,
    EAMSGD,
    AccuracyEvaluator,
    MinMaxTransformer,
    ModelPredictor,
    OneHotTransformer,
    SingleTrainer,
    SynchronousDistributedTrainer,
)
from distkeras_tpu.data.loaders import mnist
from distkeras_tpu.models.zoo import mnist_mlp


def accuracy_of(model, test):
    pred = ModelPredictor(model, batch_size=256).predict(test)
    return AccuracyEvaluator(label_col="label").evaluate(pred)


def run_scheme(
    name, make_trainer, model_seed, train, test, rounds, target,
    model_fn=None,
):
    """Train round-by-round (1 epoch per round), recording the cumulative
    wall-clock and test accuracy after each — the accuracy-vs-time curve."""
    model_fn = model_fn or (lambda seed: mnist_mlp(hidden=64, seed=seed))
    model = model_fn(model_seed)
    curve = []
    elapsed = 0.0
    samples = 0
    for r in range(rounds):
        trainer = make_trainer(model)
        t0 = time.perf_counter()
        model = trainer.train(train, shuffle=True)
        elapsed += time.perf_counter() - t0
        samples += len(train)
        acc = accuracy_of(model, test)
        curve.append({"round": r + 1, "seconds": round(elapsed, 2), "accuracy": acc})
        print(f"  {name}: round {r + 1}  t={elapsed:.1f}s  acc={acc:.4f}")
        if acc >= target:
            break
    time_to_target = next(
        (c["seconds"] for c in curve if c["accuracy"] >= target), None
    )
    return {
        "optimizer": name,
        "curve": curve,
        "final_accuracy": curve[-1]["accuracy"],
        "seconds_total": curve[-1]["seconds"],
        "time_to_target": time_to_target,
        "samples_per_sec": round(samples / elapsed, 1),
    }


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--n", type=int, default=8192)
    ap.add_argument("--rounds", type=int, default=5)
    ap.add_argument("--workers", type=int, default=4)
    ap.add_argument("--target", type=float, default=0.95)
    ap.add_argument("--csv", default=None)
    ap.add_argument(
        "--digits",
        action="store_true",
        help="run on the REAL in-repo handwritten-digit set instead of the "
        "synthetic MNIST stand-in (writes *_digits artifact files)",
    )
    ap.add_argument("--out", default=os.path.join("examples", "experiments"))
    ap.add_argument("--cpu", action="store_true")
    args = ap.parse_args()
    from distkeras_tpu.parallel.backend import setup_backend

    # probe out-of-process: a dead TPU tunnel degrades to the virtual CPU
    # mesh instead of hanging in-process backend init (--cpu forces it)
    setup_backend(cpu=args.cpu, cpu_devices=max(args.workers, 8),
                  fallback_cpu_devices=max(args.workers, 8))
    import jax

    if args.digits:
        from distkeras_tpu.data.loaders import digits
        from distkeras_tpu.models.zoo import digits_mlp

        raw = digits(flat=True)
        ds = MinMaxTransformer(n_min=0.0, n_max=1.0, o_min=0.0, o_max=16.0)(raw)
        model_fn = lambda seed: digits_mlp(hidden=64, seed=seed)  # noqa: E731
        task = "REAL digits (in-repo CSV, 1797 rows)"
        suffix = "_digits"
    else:
        raw = mnist(path=args.csv, n=args.n, flat=True)
        ds = MinMaxTransformer(n_min=0.0, n_max=1.0, o_min=0.0, o_max=255.0)(raw)
        model_fn = None
        task = "MNIST MLP (hidden 64)"
        suffix = ""
    ds = OneHotTransformer(10, input_col="label", output_col="label_onehot")(ds)
    train, test = ds.split(0.9, seed=7)

    common = dict(
        loss="categorical_crossentropy",
        label_col="label_onehot",
        batch_size=32,
        num_epoch=1,
        seed=0,
    )
    dist = dict(
        common, num_workers=args.workers, communication_window=4, mode="threads"
    )

    # the sgd lrs were calibrated on the synthetic MNIST stand-in; the real
    # 8x8 digits task (64 low-range features, small net) trains cleanly at
    # ~4x those rates (probed: lr 0.2-0.4 single-trainer reaches ~0.94 in
    # 5 epochs vs 0.88 at 0.05)
    s = 4.0 if args.digits else 1.0
    schemes = [
        ("SingleTrainer", lambda m: SingleTrainer(
            m, "sgd", learning_rate=0.05 * s, **common)),
        ("SyncDP", lambda m: SynchronousDistributedTrainer(
            m, "sgd", learning_rate=0.05 * s, num_workers=args.workers,
            **common)),
        ("DOWNPOUR", lambda m: DOWNPOUR(
            m, "sgd", learning_rate=0.02 * s, **dist)),
        ("AEASGD", lambda m: AEASGD(
            m, "sgd", learning_rate=0.02 * s, rho=10.0, **dist)),
        ("EAMSGD", lambda m: EAMSGD(
            m, "sgd", learning_rate=0.02 * s, rho=10.0, momentum=0.3, **dist)),
        ("ADAG", lambda m: ADAG(
            m, "sgd", learning_rate=0.05 * s, **dist)),
        ("DynSGD", lambda m: DynSGD(
            m, "sgd", learning_rate=0.02 * s, **dist)),
    ]

    platform = jax.devices()[0].platform
    print(f"platform: {platform}, train={len(train)}, test={len(test)}")
    results = []
    for name, make in schemes:
        print(f"== {name}")
        results.append(
            run_scheme(
                name, make, 0, train, test, args.rounds, args.target,
                model_fn=model_fn,
            )
        )

    os.makedirs(args.out, exist_ok=True)
    payload = {
        "platform": platform,
        "device_kind": jax.devices()[0].device_kind,
        "task": task,
        "n_train": len(train),
        "workers": args.workers,
        "target_accuracy": args.target,
        "results": results,
    }
    out_json = os.path.join(args.out, f"optimizer_comparison{suffix}.json")
    with open(out_json, "w") as f:
        json.dump(payload, f, indent=2)

    lines = [
        "# Optimizer comparison — accuracy vs time",
        "",
        f"{task}, {len(train)} train rows, "
        f"{args.workers} workers, platform `{platform}` "
        f"({jax.devices()[0].device_kind}). One epoch per round; "
        f"target accuracy {args.target}. Reproduce: "
        f"`python examples/optimizer_comparison.py{' --digits' if suffix else ''}`.",
        "",
        "| optimizer | time to target (s) | final acc | total time (s) | samples/sec |",
        "|---|---|---|---|---|",
    ]
    for r in results:
        ttt = f"{r['time_to_target']:.1f}" if r["time_to_target"] else "—"
        lines.append(
            f"| {r['optimizer']} | {ttt} | {r['final_accuracy']:.4f} "
            f"| {r['seconds_total']:.1f} | {r['samples_per_sec']:.0f} |"
        )
    with open(os.path.join(args.out, f"optimizer_comparison{suffix}.md"), "w") as f:
        f.write("\n".join(lines) + "\n")
    print(f"wrote {args.out}/optimizer_comparison{suffix}.{{json,md}}")


if __name__ == "__main__":
    main()
