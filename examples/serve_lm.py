"""Export -> serve -> query: the serving subsystem end to end.

Trains the toy successor-language LM (token t+1 = token t + 1 mod V, so
correct serving is verifiable at a glance), quantizes it to an int8
serving bundle on disk, boots a ``ServingEngine`` FROM THAT BUNDLE (what
a serving host does — the f32 training master never ships), fronts it
with the TCP ``ServingServer``, and then acts as its own traffic: a
burst of concurrent mixed-length ``generate`` calls, a ``predict``
round trip, ``stats``, and a graceful ``stop`` that drains in-flight
work.

Usage:
    python examples/serve_lm.py [--cpu] [--seq 64] [--slots 4]
                                [--speculative [--draft-bundle PATH]]
                                [--fleet N]
                                [--temperature T [--top-p P] [--n N]]

``--temperature`` adds the per-request SAMPLING demo: a seeded sampled
generate (replayed and asserted token-identical — serving sampling is
replay-deterministic), and with ``--n N`` the request decodes N
parallel completions via copy-on-write page forks, printing the n
streams and the pool's shared-page stats.
"""

from __future__ import annotations

import argparse
import os
import sys
import tempfile
import threading
import time

import numpy as np

sys.path.insert(0, ".")


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--seq", type=int, default=64)
    ap.add_argument("--vocab", type=int, default=32)
    ap.add_argument("--slots", type=int, default=4)
    ap.add_argument("--epochs", type=int, default=4)
    ap.add_argument("--speculative", action="store_true",
                    help="serve with speculative decoding: model-free "
                    "prompt-lookup drafting by default, or a trained "
                    "draft LM with --draft-bundle; outputs stay exactly "
                    "the greedy decode")
    ap.add_argument("--draft-bundle", metavar="PATH", default=None,
                    help="with --speculative: train a small draft LM, "
                    "persist it as a quantized serving bundle at PATH, "
                    "and serve draft-and-verify FROM THAT BUNDLE (the "
                    "second-bundle flow a speculative serving host runs)")
    ap.add_argument("--fleet", type=int, metavar="N", default=None,
                    help="serve N engine replicas behind the prefix-"
                    "affinity FleetRouter (all booted from the one "
                    "bundle), then demo a zero-downtime rolling bundle "
                    "upgrade")
    ap.add_argument("--temperature", type=float, default=None,
                    help="demo per-request SAMPLED decode at this "
                    "temperature (seeded: same seed, same tokens — "
                    "replayed and asserted)")
    ap.add_argument("--top-p", type=float, default=None,
                    help="nucleus filter for the sampled demo "
                    "(requires --temperature)")
    ap.add_argument("--n", type=int, default=1, metavar="N",
                    help="parallel completions per sampled request, "
                    "decoded via copy-on-write slot forks on the paged "
                    "KV cache (prints shared-page stats)")
    ap.add_argument("--cpu", action="store_true")
    args = ap.parse_args()
    if args.top_p is not None and args.temperature is None:
        ap.error("--top-p filters sampling; pass --temperature too")
    if args.n < 1:
        ap.error("--n must be >= 1")
    if args.n > 1 and args.temperature is None:
        ap.error("--n N parallel completions sample; pass --temperature")
    if (args.temperature is not None) and args.fleet:
        ap.error("--temperature and --fleet are separate demos; pick one")
    if args.draft_bundle and not args.speculative:
        # fail BEFORE training, not after a long run
        ap.error("--draft-bundle feeds the speculative drafter; "
                 "pass --speculative too")
    if args.fleet is not None and args.fleet < 2:
        ap.error("--fleet N needs N >= 2 (one replica is just a "
                 "server; the router exists to spread and fail over)")
    if args.fleet and args.speculative:
        # each knob is its own demo; N speculative engines would just
        # multiply boot time without showing anything new
        ap.error("--fleet and --speculative are separate demos; "
                 "pick one")

    from distkeras_tpu.parallel.backend import setup_backend

    setup_backend(cpu=args.cpu, cpu_devices=1, fallback_cpu_devices=1)

    from distkeras_tpu import SingleTrainer
    from distkeras_tpu.data.dataset import Dataset
    from distkeras_tpu.models import zoo
    from distkeras_tpu.ops.quantization import quantize_model
    from distkeras_tpu.serving import ServingClient, ServingEngine, ServingServer
    from distkeras_tpu.utils.serialization import save_serving_bundle

    # -- train the successor LM --------------------------------------------
    rng = np.random.default_rng(0)
    starts = rng.integers(0, args.vocab, 1024)
    xs = ((starts[:, None] + np.arange(args.seq)[None, :]) % args.vocab
          ).astype(np.int32)
    ds = Dataset({"features": xs, "label": xs})
    model = zoo.transformer_lm(
        vocab_size=args.vocab, seq_len=args.seq, d_model=64, num_heads=4,
        depth=2, seed=0,
    )
    trained = SingleTrainer(
        model, "adam", loss="next_token_crossentropy", learning_rate=2e-3,
        batch_size=32, num_epoch=args.epochs, seed=0,
    ).train(ds)

    # -- optionally train + export the DRAFT bundle --------------------------
    spec_kw = {}
    if args.speculative:
        spec_kw = dict(speculative="ngram", draft_k=4)
        if args.draft_bundle:
            # quarter-width single-block draft: cheap enough that its
            # per-round k+1 steps cost well under one target step
            draft = zoo.transformer_lm(
                vocab_size=args.vocab, seq_len=args.seq,
                d_model=16, num_heads=2, depth=1, seed=1,
            )
            draft_t = SingleTrainer(
                draft, "adam", loss="next_token_crossentropy",
                learning_rate=2e-3, batch_size=32,
                num_epoch=args.epochs, seed=0,
            ).train(ds)
            save_serving_bundle(
                args.draft_bundle, quantize_model(draft_t.copy())
            )
            print(f"draft bundle: {os.path.getsize(args.draft_bundle)} "
                  f"bytes at {args.draft_bundle}")
            spec_kw = dict(speculative="draft",
                           draft_bundle=args.draft_bundle, draft_k=4)

    # -- export the serving bundle, boot the engine from DISK ---------------
    with tempfile.TemporaryDirectory() as tmp:
        bundle = os.path.join(tmp, "lm_int8.dkt")
        save_serving_bundle(bundle, quantize_model(trained.copy()))
        print(f"serving bundle: {os.path.getsize(bundle)} bytes")
        if args.fleet:
            serve_fleet(args, bundle)
            return
        paged_kw = {}
        if args.n > 1:
            # n-parallel completions ride copy-on-write page forks:
            # serve the paged KV cache and keep n slots available
            paged_kw = dict(paged=True, page_size=8)
            args.slots = max(args.slots, args.n)
        engine = ServingEngine.from_bundle(
            bundle, num_slots=args.slots, queue_capacity=32, **spec_kw,
            **paged_kw,
        )
        server = ServingServer(engine).start()
        print(f"serving on {server.host}:{server.port} "
              f"({args.slots} slots"
              + (f", speculative={spec_kw['speculative']}"
                 if spec_kw else "") + ")")

        # -- concurrent mixed-length clients --------------------------------
        prompts = [
            np.array([3 % args.vocab], np.int32),
            np.array([x % args.vocab for x in (10, 11, 12)], np.int32),
            np.arange(5, dtype=np.int32) % args.vocab,
            np.array([x % args.vocab for x in (20, 21)], np.int32),
        ]
        steps = min(10, args.seq // 2)
        results = [None] * len(prompts)

        def client(i):
            with ServingClient(server.host, server.port) as c:
                results[i] = c.generate(prompts[i], steps)

        t0 = time.time()
        threads = [threading.Thread(target=client, args=(i,))
                   for i in range(len(prompts))]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        dt = time.time() - t0
        for row in results:
            print("served decode:", row.tolist())  # must count upward
        print(f"{len(prompts)} concurrent requests x {steps} tokens "
              f"in {dt:.2f}s")

        # -- per-request sampling demo (--temperature [--top-p] [--n]) ------
        if args.temperature is not None:
            from distkeras_tpu.serving import SamplingParams

            sp = SamplingParams(
                temperature=args.temperature, top_p=args.top_p,
                seed=7, n=args.n,
            )
            with ServingClient(server.host, server.port) as c:
                out = c.generate(prompts[0], steps, sampling=sp)
                outs = out if isinstance(out, list) else [out]
                for j, row in enumerate(outs):
                    print(f"sampled completion {j}: {row.tolist()}")
                replay = c.generate(prompts[0], steps, sampling=sp)
                replays = (
                    replay if isinstance(replay, list) else [replay]
                )
                assert all(
                    np.array_equal(a, b)
                    for a, b in zip(outs, replays)
                ), "same seed must replay identical samples"
                print(f"replayed {len(outs)} completion(s) "
                      f"token-identically (seed {sp.seed})")
                if args.n > 1:
                    pg = c.stats()["paged"]
                    print(f"shared pages: {pg['shared_pages']} shared / "
                          f"{pg['pages_in_use']} in use, "
                          f"{pg['cow_copies']} CoW copies "
                          f"({args.n} completions forked from one "
                          f"prefill)")

        with ServingClient(server.host, server.port) as c:
            logits = c.predict(xs[:2])
            print(f"predict: logits {logits.shape} over the vocab")
            st = c.stats()
            print(f"stats: {st['completed']} completed, mean batch "
                  f"occupancy {st['mean_batch_occupancy']:.2f}, "
                  f"prefill buckets {st['compiled_prefill_buckets']}")
            if args.speculative:
                sp = st["speculative"]
                print(f"speculative[{sp['draft_source']}]: "
                      f"{sp['windows']} verify windows, "
                      f"{sp['mean_tokens_per_window']:.2f} tokens/window, "
                      f"{sp['accepted_draft_tokens']} draft tokens "
                      f"accepted / {sp['rejected_draft_tokens']} "
                      f"rejected, {sp['fallback_steps']} plain-step "
                      f"fallbacks")
            c.stop()  # graceful: drains in-flight work, then closes
        server.shutdown()
        print("drained and stopped")


def serve_fleet(args, bundle):
    """--fleet N: the replicated flow a production serving host runs —
    N replicas booted from ONE bundle behind the prefix-affinity
    router, concurrent shared-header clients (placement visible via
    the ``served_by`` reply stamp), then a zero-downtime rolling
    bundle upgrade and proof the upgraded fleet still serves."""
    from distkeras_tpu.serving import FleetController, ServingClient

    ctl = FleetController(
        bundle, replicas=args.fleet, num_slots=args.slots,
        queue_capacity=32,
    ).start()
    try:
        host, port = ctl.endpoint
        print(f"fleet: {args.fleet} replicas behind router "
              f"{host}:{port} "
              f"({', '.join('%s:%s' % r.endpoint for r in ctl.replicas)})")

        # shared-header traffic: every prompt extends one 16-token
        # header, so prefix affinity must land ALL of them on ONE
        # replica (where the shared KV lives)
        header = (np.arange(16, dtype=np.int32) * 3 + 1) % args.vocab
        prompts = [
            np.concatenate([header,
                            np.asarray(sfx, np.int32) % args.vocab])
            for sfx in ([17], [17, 18], [17, 18, 19], [17, 18, 19, 20])
        ]
        steps = min(10, args.seq // 2)
        results = [None] * len(prompts)
        served = [None] * len(prompts)

        def client(i):
            with ServingClient(host, port) as c:
                results[i] = c.generate(prompts[i], steps)
                served[i] = c.last_served_by

        t0 = time.time()
        threads = [threading.Thread(target=client, args=(i,))
                   for i in range(len(prompts))]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        dt = time.time() - t0
        for row in results:
            print("served decode:", row.tolist())  # must count upward
        homes = {s for s in served}
        print(f"{len(prompts)} shared-header requests x {steps} tokens "
              f"in {dt:.2f}s, served by {len(homes)} replica(s): "
              f"{sorted('%s:%s' % h for h in homes)}")

        # rolling upgrade: same bundle stands in for the next training
        # checkpoint — the sequence (boot replacement, health-gate in,
        # drain old, stop old) is identical either way
        ledger = ctl.rollover(bundle)
        print(f"rollover complete: {len(ledger['replaced'])} replicas "
              f"upgraded in {ledger['seconds']}s, zero requests "
              f"dropped")
        with ServingClient(host, port) as c:
            out = c.generate(prompts[0], steps)
            print("served decode (upgraded fleet):", out.tolist())
            h = c.health()
            print(f"fleet health: {h['status']}, "
                  f"{h['active_replicas']} replicas in rotation")
    finally:
        ctl.stop()
    print("drained and stopped")


if __name__ == "__main__":
    main()
