"""Export -> serve -> query: the serving subsystem end to end.

Trains the toy successor-language LM (token t+1 = token t + 1 mod V, so
correct serving is verifiable at a glance), quantizes it to an int8
serving bundle on disk, boots a ``ServingEngine`` FROM THAT BUNDLE (what
a serving host does — the f32 training master never ships), fronts it
with the TCP ``ServingServer``, and then acts as its own traffic: a
burst of concurrent mixed-length ``generate`` calls, a ``predict``
round trip, ``stats``, and a graceful ``stop`` that drains in-flight
work.

Usage:
    python examples/serve_lm.py [--cpu] [--seq 64] [--slots 4]
"""

from __future__ import annotations

import argparse
import os
import sys
import tempfile
import threading
import time

import numpy as np

sys.path.insert(0, ".")


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--seq", type=int, default=64)
    ap.add_argument("--vocab", type=int, default=32)
    ap.add_argument("--slots", type=int, default=4)
    ap.add_argument("--epochs", type=int, default=4)
    ap.add_argument("--cpu", action="store_true")
    args = ap.parse_args()

    from distkeras_tpu.parallel.backend import setup_backend

    setup_backend(cpu=args.cpu, cpu_devices=1, fallback_cpu_devices=1)

    from distkeras_tpu import SingleTrainer
    from distkeras_tpu.data.dataset import Dataset
    from distkeras_tpu.models import zoo
    from distkeras_tpu.ops.quantization import quantize_model
    from distkeras_tpu.serving import ServingClient, ServingEngine, ServingServer
    from distkeras_tpu.utils.serialization import save_serving_bundle

    # -- train the successor LM --------------------------------------------
    rng = np.random.default_rng(0)
    starts = rng.integers(0, args.vocab, 1024)
    xs = ((starts[:, None] + np.arange(args.seq)[None, :]) % args.vocab
          ).astype(np.int32)
    ds = Dataset({"features": xs, "label": xs})
    model = zoo.transformer_lm(
        vocab_size=args.vocab, seq_len=args.seq, d_model=64, num_heads=4,
        depth=2, seed=0,
    )
    trained = SingleTrainer(
        model, "adam", loss="next_token_crossentropy", learning_rate=2e-3,
        batch_size=32, num_epoch=args.epochs, seed=0,
    ).train(ds)

    # -- export the serving bundle, boot the engine from DISK ---------------
    with tempfile.TemporaryDirectory() as tmp:
        bundle = os.path.join(tmp, "lm_int8.dkt")
        save_serving_bundle(bundle, quantize_model(trained.copy()))
        print(f"serving bundle: {os.path.getsize(bundle)} bytes")
        engine = ServingEngine.from_bundle(
            bundle, num_slots=args.slots, queue_capacity=32,
        )
        server = ServingServer(engine).start()
        print(f"serving on {server.host}:{server.port} "
              f"({args.slots} slots)")

        # -- concurrent mixed-length clients --------------------------------
        prompts = [
            np.array([3 % args.vocab], np.int32),
            np.array([x % args.vocab for x in (10, 11, 12)], np.int32),
            np.arange(5, dtype=np.int32) % args.vocab,
            np.array([x % args.vocab for x in (20, 21)], np.int32),
        ]
        steps = min(10, args.seq // 2)
        results = [None] * len(prompts)

        def client(i):
            with ServingClient(server.host, server.port) as c:
                results[i] = c.generate(prompts[i], steps)

        t0 = time.time()
        threads = [threading.Thread(target=client, args=(i,))
                   for i in range(len(prompts))]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        dt = time.time() - t0
        for row in results:
            print("served decode:", row.tolist())  # must count upward
        print(f"{len(prompts)} concurrent requests x {steps} tokens "
              f"in {dt:.2f}s")

        with ServingClient(server.host, server.port) as c:
            logits = c.predict(xs[:2])
            print(f"predict: logits {logits.shape} over the vocab")
            st = c.stats()
            print(f"stats: {st['completed']} completed, mean batch "
                  f"occupancy {st['mean_batch_occupancy']:.2f}, "
                  f"prefill buckets {st['compiled_prefill_buckets']}")
            c.stop()  # graceful: drains in-flight work, then closes
        server.shutdown()
        print("drained and stopped")


if __name__ == "__main__":
    main()
