"""Export -> serve -> query: the serving subsystem end to end.

Trains the toy successor-language LM (token t+1 = token t + 1 mod V, so
correct serving is verifiable at a glance), quantizes it to an int8
serving bundle on disk, boots a ``ServingEngine`` FROM THAT BUNDLE (what
a serving host does — the f32 training master never ships), fronts it
with the TCP ``ServingServer``, and then acts as its own traffic: a
burst of concurrent mixed-length ``generate`` calls, a ``predict``
round trip, ``stats``, and a graceful ``stop`` that drains in-flight
work.

Usage:
    python examples/serve_lm.py [--cpu] [--seq 64] [--slots 4]
"""

from __future__ import annotations

import argparse
import os
import sys
import tempfile
import threading
import time

import numpy as np

sys.path.insert(0, ".")


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--seq", type=int, default=64)
    ap.add_argument("--vocab", type=int, default=32)
    ap.add_argument("--slots", type=int, default=4)
    ap.add_argument("--epochs", type=int, default=4)
    ap.add_argument("--speculative", action="store_true",
                    help="serve with speculative decoding: model-free "
                    "prompt-lookup drafting by default, or a trained "
                    "draft LM with --draft-bundle; outputs stay exactly "
                    "the greedy decode")
    ap.add_argument("--draft-bundle", metavar="PATH", default=None,
                    help="with --speculative: train a small draft LM, "
                    "persist it as a quantized serving bundle at PATH, "
                    "and serve draft-and-verify FROM THAT BUNDLE (the "
                    "second-bundle flow a speculative serving host runs)")
    ap.add_argument("--cpu", action="store_true")
    args = ap.parse_args()
    if args.draft_bundle and not args.speculative:
        # fail BEFORE training, not after a long run
        ap.error("--draft-bundle feeds the speculative drafter; "
                 "pass --speculative too")

    from distkeras_tpu.parallel.backend import setup_backend

    setup_backend(cpu=args.cpu, cpu_devices=1, fallback_cpu_devices=1)

    from distkeras_tpu import SingleTrainer
    from distkeras_tpu.data.dataset import Dataset
    from distkeras_tpu.models import zoo
    from distkeras_tpu.ops.quantization import quantize_model
    from distkeras_tpu.serving import ServingClient, ServingEngine, ServingServer
    from distkeras_tpu.utils.serialization import save_serving_bundle

    # -- train the successor LM --------------------------------------------
    rng = np.random.default_rng(0)
    starts = rng.integers(0, args.vocab, 1024)
    xs = ((starts[:, None] + np.arange(args.seq)[None, :]) % args.vocab
          ).astype(np.int32)
    ds = Dataset({"features": xs, "label": xs})
    model = zoo.transformer_lm(
        vocab_size=args.vocab, seq_len=args.seq, d_model=64, num_heads=4,
        depth=2, seed=0,
    )
    trained = SingleTrainer(
        model, "adam", loss="next_token_crossentropy", learning_rate=2e-3,
        batch_size=32, num_epoch=args.epochs, seed=0,
    ).train(ds)

    # -- optionally train + export the DRAFT bundle --------------------------
    spec_kw = {}
    if args.speculative:
        spec_kw = dict(speculative="ngram", draft_k=4)
        if args.draft_bundle:
            # quarter-width single-block draft: cheap enough that its
            # per-round k+1 steps cost well under one target step
            draft = zoo.transformer_lm(
                vocab_size=args.vocab, seq_len=args.seq,
                d_model=16, num_heads=2, depth=1, seed=1,
            )
            draft_t = SingleTrainer(
                draft, "adam", loss="next_token_crossentropy",
                learning_rate=2e-3, batch_size=32,
                num_epoch=args.epochs, seed=0,
            ).train(ds)
            save_serving_bundle(
                args.draft_bundle, quantize_model(draft_t.copy())
            )
            print(f"draft bundle: {os.path.getsize(args.draft_bundle)} "
                  f"bytes at {args.draft_bundle}")
            spec_kw = dict(speculative="draft",
                           draft_bundle=args.draft_bundle, draft_k=4)

    # -- export the serving bundle, boot the engine from DISK ---------------
    with tempfile.TemporaryDirectory() as tmp:
        bundle = os.path.join(tmp, "lm_int8.dkt")
        save_serving_bundle(bundle, quantize_model(trained.copy()))
        print(f"serving bundle: {os.path.getsize(bundle)} bytes")
        engine = ServingEngine.from_bundle(
            bundle, num_slots=args.slots, queue_capacity=32, **spec_kw,
        )
        server = ServingServer(engine).start()
        print(f"serving on {server.host}:{server.port} "
              f"({args.slots} slots"
              + (f", speculative={spec_kw['speculative']}"
                 if spec_kw else "") + ")")

        # -- concurrent mixed-length clients --------------------------------
        prompts = [
            np.array([3 % args.vocab], np.int32),
            np.array([x % args.vocab for x in (10, 11, 12)], np.int32),
            np.arange(5, dtype=np.int32) % args.vocab,
            np.array([x % args.vocab for x in (20, 21)], np.int32),
        ]
        steps = min(10, args.seq // 2)
        results = [None] * len(prompts)

        def client(i):
            with ServingClient(server.host, server.port) as c:
                results[i] = c.generate(prompts[i], steps)

        t0 = time.time()
        threads = [threading.Thread(target=client, args=(i,))
                   for i in range(len(prompts))]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        dt = time.time() - t0
        for row in results:
            print("served decode:", row.tolist())  # must count upward
        print(f"{len(prompts)} concurrent requests x {steps} tokens "
              f"in {dt:.2f}s")

        with ServingClient(server.host, server.port) as c:
            logits = c.predict(xs[:2])
            print(f"predict: logits {logits.shape} over the vocab")
            st = c.stats()
            print(f"stats: {st['completed']} completed, mean batch "
                  f"occupancy {st['mean_batch_occupancy']:.2f}, "
                  f"prefill buckets {st['compiled_prefill_buckets']}")
            if args.speculative:
                sp = st["speculative"]
                print(f"speculative[{sp['draft_source']}]: "
                      f"{sp['windows']} verify windows, "
                      f"{sp['mean_tokens_per_window']:.2f} tokens/window, "
                      f"{sp['accepted_draft_tokens']} draft tokens "
                      f"accepted / {sp['rejected_draft_tokens']} "
                      f"rejected, {sp['fallback_steps']} plain-step "
                      f"fallbacks")
            c.stop()  # graceful: drains in-flight work, then closes
        server.shutdown()
        print("drained and stopped")


if __name__ == "__main__":
    main()
