"""Long-context sequence classification — TRAINED with ring attention.

No reference counterpart (the reference's workloads are MLP/CNN/tabular —
SURVEY §5.7); this example shows the TPU rebuild's sequence-parallel path:
a transformer classifier trained end-to-end at a sequence length sharded
over a ``Mesh(("seq",))`` — K/V blocks rotate between devices via ppermute
with an online softmax, gradients flow back through the ring, and the
per-device attention footprint is O(T/N · T/N) instead of O(T · T).

Usage:
    python examples/long_context.py [--seq 2048] [--cpu]
    XLA_FLAGS=--xla_force_host_platform_device_count=8 \
        python examples/long_context.py --seq 1024 --cpu   # 8-way sharded
"""

from __future__ import annotations

import argparse
import sys
import time

sys.path.insert(0, ".")


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--seq", type=int, default=2048)
    ap.add_argument("--vocab", type=int, default=64)
    ap.add_argument("--d-model", type=int, default=64)
    ap.add_argument("--heads", type=int, default=4)
    ap.add_argument("--depth", type=int, default=2)
    ap.add_argument("--batch", type=int, default=4)
    ap.add_argument("--rows", type=int, default=512)
    ap.add_argument("--epochs", type=int, default=2)
    ap.add_argument("--sp-mode", choices=["ring", "ulysses"], default="ring",
                    help="how attention crosses the sequence shards: the "
                         "K/V ppermute ring, or Ulysses all-to-all head "
                         "sharding (heads divisible by the device count)")
    ap.add_argument("--cpu", action="store_true",
                    help="force the CPU backend (virtual multi-device mesh "
                         "via XLA_FLAGS=--xla_force_host_platform_device_count=N)")
    args = ap.parse_args()
    from distkeras_tpu.parallel.backend import setup_backend

    # probe out-of-process: a dead TPU tunnel degrades to the virtual CPU
    # mesh instead of hanging in-process backend init (--cpu forces it)
    setup_backend(cpu=args.cpu, cpu_devices=8, fallback_cpu_devices=8)

    import jax
    import numpy as np
    from jax.sharding import Mesh

    from distkeras_tpu import SequenceParallelTrainer
    from distkeras_tpu.data import loaders
    from distkeras_tpu.data.transformers import OneHotTransformer
    from distkeras_tpu.evaluators import AccuracyEvaluator
    from distkeras_tpu.models import zoo
    from distkeras_tpu.parallel.ring_attention import attach_ring_attention
    from distkeras_tpu.predictors import ModelPredictor

    devices = jax.devices()
    n = len(devices)
    if args.seq % n:
        raise SystemExit(
            f"--seq {args.seq} must be divisible by the device count {n}"
        )
    if args.sp_mode == "ulysses" and args.heads % n:
        raise SystemExit(
            f"--sp-mode ulysses shards heads: --heads {args.heads} must be "
            f"divisible by the device count {n}"
        )
    print(f"devices: {n} x {devices[0].platform}; seq {args.seq} "
          f"-> {args.seq // n} tokens/device")

    # TRAIN at the full --seq length with the token axis sharded over the
    # mesh: every gradient step back-propagates through the ppermute ring
    # (per-device attention memory O((T/N)^2) instead of O(T^2))
    ds = loaders.synthetic_sequences(
        n=args.rows, seq_len=args.seq, vocab=args.vocab, seed=0
    )
    ds = OneHotTransformer(2, output_col="label_onehot").transform(ds)
    train, test = ds.split(0.85, seed=0)
    model = zoo.transformer_classifier(
        vocab_size=args.vocab, seq_len=args.seq, d_model=args.d_model,
        num_heads=args.heads, depth=args.depth,
    )
    trainer = SequenceParallelTrainer(
        model, "adam", "categorical_crossentropy",
        batch_size=args.batch, num_epoch=args.epochs,
        label_col="label_onehot", sp_mode=args.sp_mode,
    )
    t0 = time.perf_counter()
    trained = trainer.train(train, shuffle=True)
    train_s = time.perf_counter() - t0
    hist = trainer.get_history()
    # batches() drops the sub-batch remainder; count rows actually consumed
    rows_per_epoch = (len(train) // args.batch) * args.batch
    tokens_per_sec = rows_per_epoch * args.seq * args.epochs / train_s
    print(f"sequence-parallel training at {args.seq} tokens over "
          f"{trainer.num_workers} devices: {train_s:.1f}s "
          f"({tokens_per_sec:,.0f} tokens/s), "
          f"loss {hist[0]['loss']:.4f} -> {hist[-1]['loss']:.4f}")

    # evaluate long-context: re-attach sharded attention for inference
    # (training detaches its hook; the returned model is dense by default)
    mesh = Mesh(np.array(devices), ("seq",))
    if args.sp_mode == "ulysses":
        from distkeras_tpu.parallel.ulysses import attach_ulysses_attention

        attached = attach_ulysses_attention(trained, mesh)
    else:
        attached = attach_ring_attention(trained, mesh)
    acc = AccuracyEvaluator(label_col="label").evaluate(
        ModelPredictor(trained, batch_size=max(args.batch, 8)).predict(test)
    )
    print(f"long-context ({args.seq} tokens, {args.sp_mode} attention on "
          f"{attached} blocks) test accuracy: {acc:.4f}")


if __name__ == "__main__":
    main()
