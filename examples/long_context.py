"""Long-context sequence classification with ring attention.

No reference counterpart (the reference's workloads are MLP/CNN/tabular —
SURVEY §5.7); this example shows the TPU rebuild's sequence-parallel path:
a transformer classifier whose attention runs as ring attention over a
``Mesh(("seq",))`` — K/V blocks rotate between devices via ppermute with an
online softmax, so the per-device attention footprint is O(T/N · T/N)
instead of O(T · T).

Usage:
    python examples/long_context.py [--seq 2048] [--cpu]
"""

from __future__ import annotations

import argparse
import sys
import time

sys.path.insert(0, ".")


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--seq", type=int, default=2048)
    ap.add_argument("--vocab", type=int, default=64)
    ap.add_argument("--d-model", type=int, default=64)
    ap.add_argument("--heads", type=int, default=4)
    ap.add_argument("--depth", type=int, default=2)
    ap.add_argument("--batch", type=int, default=4)
    ap.add_argument("--train-steps", type=int, default=20)
    ap.add_argument("--cpu", action="store_true",
                    help="force the CPU backend (virtual multi-device mesh "
                         "via XLA_FLAGS=--xla_force_host_platform_device_count=N)")
    args = ap.parse_args()
    if args.cpu:
        import jax

        jax.config.update("jax_platforms", "cpu")

    import jax
    import jax.numpy as jnp
    import numpy as np
    from jax.sharding import Mesh

    from distkeras_tpu import SingleTrainer
    from distkeras_tpu.data import loaders
    from distkeras_tpu.data.transformers import OneHotTransformer
    from distkeras_tpu.evaluators import AccuracyEvaluator
    from distkeras_tpu.models import zoo
    from distkeras_tpu.parallel.ring_attention import attach_ring_attention
    from distkeras_tpu.predictors import ModelPredictor

    devices = jax.devices()
    n = len(devices)
    if args.seq % n:
        raise SystemExit(f"--seq {args.seq} must divide the {n} devices")
    print(f"devices: {n} x {devices[0].platform}; seq {args.seq} "
          f"-> {args.seq // n} tokens/device")

    # 1) train at a short length (dense attention) — position embeddings are
    #    length-specific, so train and serve at the lengths you need
    short = 128
    ds = loaders.synthetic_sequences(
        n=2048, seq_len=short, vocab=args.vocab, seed=0
    )
    ds = OneHotTransformer(2, output_col="label_onehot").transform(ds)
    train, test = ds.split(0.85, seed=0)
    model = zoo.transformer_classifier(
        vocab_size=args.vocab, seq_len=short, d_model=args.d_model,
        num_heads=args.heads, depth=args.depth,
    )
    t = SingleTrainer(model, "adam", "categorical_crossentropy",
                      batch_size=64, num_epoch=2, label_col="label_onehot")
    trained = t.train(train, shuffle=True)
    acc = AccuracyEvaluator(label_col="label").evaluate(
        ModelPredictor(trained, batch_size=256).predict(test)
    )
    print(f"short-context ({short} tokens) test accuracy: {acc:.4f}")

    # 2) long-context inference: same architecture at --seq tokens, ring
    #    attention over the device mesh
    mesh = Mesh(np.array(devices), ("seq",))
    long_model = zoo.transformer_classifier(
        vocab_size=args.vocab, seq_len=args.seq, d_model=args.d_model,
        num_heads=args.heads, depth=args.depth,
    )
    attached = attach_ring_attention(long_model, mesh)
    print(f"ring attention attached to {attached} blocks")

    long_ds = loaders.synthetic_sequences(
        n=args.batch, seq_len=args.seq, vocab=args.vocab, seed=3
    )
    x = jnp.asarray(long_ds["features"])
    t0 = time.perf_counter()
    y, _ = long_model.apply(long_model.params, long_model.state, x)
    jax.block_until_ready(y)
    compile_s = time.perf_counter() - t0
    t0 = time.perf_counter()
    y, _ = long_model.apply(long_model.params, long_model.state, x)
    jax.block_until_ready(y)
    print(f"long-context forward ({args.seq} tokens, batch {args.batch}): "
          f"{time.perf_counter() - t0:.3f}s (first call {compile_s:.1f}s), "
          f"output {y.shape}")


if __name__ == "__main__":
    main()
