"""CIFAR-10 CNN — ADAG (Hermans' accumulated gradient normalization;
BASELINE config 4)."""

from __future__ import annotations

import argparse
import sys
import time

sys.path.insert(0, ".")

from distkeras_tpu import (
    ADAG,
    AccuracyEvaluator,
    LabelIndexTransformer,
    MinMaxTransformer,
    ModelPredictor,
    OneHotTransformer,
)
from distkeras_tpu.data.loaders import synthetic_cifar10
from distkeras_tpu.models.zoo import cifar10_cnn


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--epochs", type=int, default=3)
    ap.add_argument("--batch", type=int, default=64)
    ap.add_argument("--workers", type=int, default=4)
    ap.add_argument("--n", type=int, default=8192)
    ap.add_argument("--cpu", action="store_true",
                    help="force the CPU backend (virtual multi-device mesh "
                         "via XLA_FLAGS=--xla_force_host_platform_device_count=N)")
    args = ap.parse_args()
    from distkeras_tpu.parallel.backend import setup_backend

    # probe out-of-process: a dead TPU tunnel degrades to CPU instead of
    # hanging in-process backend init (--cpu forces it)
    setup_backend(cpu=args.cpu, cpu_devices=max(args.workers, 8),
                  fallback_cpu_devices=max(args.workers, 8))

    raw = synthetic_cifar10(n=args.n)
    ds = MinMaxTransformer(0.0, 1.0, 0.0, 255.0)(raw)
    ds = OneHotTransformer(10, input_col="label", output_col="label_onehot")(ds)
    train, test = ds.split(0.9, seed=7)

    model = cifar10_cnn(seed=0, bn_momentum=0.9)  # short-run eval stats
    # sgd lr 0.05 (benchmarks.py config-4 calibration): ADAG's center moves
    # by -lr * mean-grad per commit regardless of the local optimizer, and
    # adam's default 1e-3 leaves the center nearly frozen at demo scales
    trainer = ADAG(
        model, worker_optimizer="sgd", learning_rate=0.05,
        loss="categorical_crossentropy",
        label_col="label_onehot", batch_size=args.batch,
        num_epoch=args.epochs, num_workers=args.workers,
        communication_window=5, compute_dtype="bfloat16",
    )
    t0 = time.time()
    trained = trainer.train(train, shuffle=True)
    print(f"trained in {time.time() - t0:.1f}s; "
          f"PS updates: {trainer.parameter_server.num_updates}")

    pred = ModelPredictor(trained).predict(test)
    pred = LabelIndexTransformer(10)(pred)
    acc = AccuracyEvaluator(
        prediction_col="prediction_index", label_col="label"
    ).evaluate(pred)
    print(f"test accuracy: {acc:.4f}")


if __name__ == "__main__":
    main()
