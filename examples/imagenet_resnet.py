"""ResNet-18 / ImageNet-scale — DynSGD staleness-aware async SGD over a
file-sharded streaming dataset (BASELINE config 5; 32 workers at full
scale, reduced here to what the local device count supports).

With no ImageNet on disk, the script WRITES synthetic ImageNet-shaped data
to ``.npz`` shards chunk by chunk (uint8, never holding the full dataset in
one array) and trains from :class:`StreamingDataset`: one shard resident
per worker at a time, preprocessing applied per chunk via ``.map``, window
staging (stack + device_put) optionally prefetched on a background thread
(``prefetch=N``; off by default — the committed v5e A/Bs measured overlap
as a median loss, see PERF.md). This is the input-pipeline shape that
feeds real ImageNet: swap the synthetic writer for shards of decoded
images.
"""

from __future__ import annotations

import argparse
import os
import sys
import tempfile
import time

sys.path.insert(0, ".")

import numpy as np

from distkeras_tpu import (
    AccuracyEvaluator,
    DynSGD,
    LabelIndexTransformer,
    ModelPredictor,
    OneHotTransformer,
)
from distkeras_tpu.data.loaders import synthetic_imagenet
from distkeras_tpu.data.streaming import ShardWriter, open_shards
from distkeras_tpu.models.zoo import resnet18

# one label->pattern mapping for every draw of the synthetic task: shards
# and the eval split must agree or the task is unlearnable (see
# loaders._spatial_prototype_classification)
PROTO_SEED = 7


def write_synthetic_shards(
    out_dir, n, num_classes, size, rows_per_shard, seed=PROTO_SEED
):
    """Generate shard files chunk by chunk — peak host memory is one chunk,
    so the on-disk dataset can exceed RAM. All shards land in ONE directory
    with one sidecar, so ``open_shards(out_dir)`` round-trips."""
    with ShardWriter(out_dir) as writer:
        written = 0
        chunk_i = 0
        while written < n:
            rows = min(rows_per_shard, n - written)
            # proto_seed pinned: every chunk (and the eval split) must
            # agree on the label->pattern mapping or the task is unlearnable
            chunk = synthetic_imagenet(
                n=rows, num_classes=num_classes, size=size,
                seed=seed + chunk_i, proto_seed=PROTO_SEED,
            )
            # uint8 on disk (as real image shards would be): 4x smaller files
            writer.add(
                {
                    "features": chunk["features"].astype(np.uint8),
                    "label": chunk["label"],
                }
            )
            written += rows
            chunk_i += 1
    return writer._paths


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--epochs", type=int, default=1)
    ap.add_argument("--batch", type=int, default=32)
    ap.add_argument("--workers", type=int, default=4)
    ap.add_argument("--classes", type=int, default=100)
    ap.add_argument("--size", type=int, default=64, help="image side length")
    ap.add_argument("--n", type=int, default=2048)
    ap.add_argument("--rows-per-shard", type=int, default=256)
    ap.add_argument("--shard-dir", default=None,
                    help="existing shard tree (skips synthetic generation)")
    ap.add_argument("--cpu", action="store_true",
                    help="force the CPU backend (virtual multi-device mesh "
                         "via XLA_FLAGS=--xla_force_host_platform_device_count=N)")
    args = ap.parse_args()
    from distkeras_tpu.parallel.backend import setup_backend

    # probe out-of-process: a dead TPU tunnel degrades to the virtual CPU
    # mesh instead of hanging in-process backend init (--cpu forces it)
    setup_backend(cpu=args.cpu, cpu_devices=max(args.workers, 8),
                  fallback_cpu_devices=max(args.workers, 8))

    def preprocess(chunk):
        x = chunk["features"].astype(np.float32) / 255.0
        onehot = np.eye(args.classes, dtype=np.float32)[chunk["label"]]
        return {"features": x, "label": chunk["label"], "label_onehot": onehot}

    if args.shard_dir:
        root = args.shard_dir
    else:
        root = tempfile.mkdtemp(prefix="dkt_imagenet_")
        t0 = time.time()
        shard_paths = write_synthetic_shards(
            root, args.n, args.classes, args.size, args.rows_per_shard
        )
        print(f"wrote {len(shard_paths)} shards under {root} "
              f"in {time.time() - t0:.1f}s (reuse with --shard-dir {root})")
    train = open_shards(root).map(preprocess)

    # held-out eval set stays in-memory (it is small)
    from distkeras_tpu.data.dataset import Dataset

    test_raw = synthetic_imagenet(
        n=max(args.n // 10, args.batch), num_classes=args.classes,
        size=args.size, seed=99, proto_seed=PROTO_SEED,
    )
    test = Dataset(
        {
            "features": np.asarray(test_raw["features"], np.float32) / 255.0,
            "label": test_raw["label"],
        }
    )
    test = OneHotTransformer(
        args.classes, input_col="label", output_col="label_onehot"
    )(test)

    model = resnet18(
        num_classes=args.classes, input_shape=(args.size, args.size, 3),
        seed=0, bn_momentum=0.9,  # short demo runs: eval stats must track
    )
    # adam lr 1e-3 (benchmarks.py config-5 calibration): a from-scratch
    # ResNet under DynSGD stays at a constant prediction with plain sgd;
    # the 1/(staleness+1) delta scaling already provides the per-worker
    # division
    trainer = DynSGD(
        model, worker_optimizer="adam", loss="categorical_crossentropy",
        learning_rate=1e-3, label_col="label_onehot", batch_size=args.batch,
        num_epoch=args.epochs, num_workers=args.workers,
        communication_window=4, compute_dtype="bfloat16",
    )
    t0 = time.time()
    trained = trainer.train(train, shuffle=True)
    print(f"trained in {time.time() - t0:.1f}s; "
          f"PS updates: {trainer.parameter_server.num_updates}")

    pred = ModelPredictor(trained, batch_size=256).predict(test)
    pred = LabelIndexTransformer(args.classes)(pred)
    acc = AccuracyEvaluator(
        prediction_col="prediction_index", label_col="label"
    ).evaluate(pred)
    print(f"test accuracy: {acc:.4f}")


if __name__ == "__main__":
    main()
