"""ResNet-18 / ImageNet-scale — DynSGD staleness-aware async SGD
(BASELINE config 5; 32 workers at full scale, reduced here to what the
local device count supports).

With no ImageNet on disk, runs on synthetic ImageNet-shaped data (smaller
spatial size by default) — the exercise is the trainer/PS machinery and the
ResNet compute graph, not the dataset.
"""

from __future__ import annotations

import argparse
import sys
import time

sys.path.insert(0, ".")

from distkeras_tpu import (
    AccuracyEvaluator,
    DynSGD,
    LabelIndexTransformer,
    MinMaxTransformer,
    ModelPredictor,
    OneHotTransformer,
)
from distkeras_tpu.data.loaders import synthetic_imagenet
from distkeras_tpu.models.zoo import resnet18


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--epochs", type=int, default=1)
    ap.add_argument("--batch", type=int, default=32)
    ap.add_argument("--workers", type=int, default=4)
    ap.add_argument("--classes", type=int, default=100)
    ap.add_argument("--size", type=int, default=64, help="image side length")
    ap.add_argument("--n", type=int, default=2048)
    ap.add_argument("--cpu", action="store_true",
                    help="force the CPU backend (virtual multi-device mesh "
                         "via XLA_FLAGS=--xla_force_host_platform_device_count=N)")
    args = ap.parse_args()
    if args.cpu:
        import jax

        jax.config.update("jax_platforms", "cpu")

    raw = synthetic_imagenet(n=args.n, num_classes=args.classes, size=args.size)
    ds = MinMaxTransformer(0.0, 1.0, 0.0, 255.0)(raw)
    ds = OneHotTransformer(
        args.classes, input_col="label", output_col="label_onehot"
    )(ds)
    train, test = ds.split(0.9, seed=7)

    model = resnet18(
        num_classes=args.classes, input_shape=(args.size, args.size, 3), seed=0
    )
    trainer = DynSGD(
        model, worker_optimizer="sgd", loss="categorical_crossentropy",
        learning_rate=0.1, label_col="label_onehot", batch_size=args.batch,
        num_epoch=args.epochs, num_workers=args.workers,
        communication_window=4, compute_dtype="bfloat16",
    )
    t0 = time.time()
    trained = trainer.train(train, shuffle=True)
    print(f"trained in {time.time() - t0:.1f}s; "
          f"PS updates: {trainer.parameter_server.num_updates}")

    pred = ModelPredictor(trained, batch_size=256).predict(test)
    pred = LabelIndexTransformer(args.classes)(pred)
    acc = AccuracyEvaluator(
        prediction_col="prediction_index", label_col="label"
    ).evaluate(pred)
    print(f"test accuracy: {acc:.4f}")


if __name__ == "__main__":
    main()
