"""REAL-data end-to-end: the reference's mnist.py pipeline shape on the
real handwritten-digit set shipped in-repo (reference: examples/mnist.py
loads real MNIST CSV; the sandbox has no downloads, so the committed
``distkeras_tpu/data/digits.csv`` — 1,797 real 8x8 images — plays that
role; VERDICT r2 missing #1).

Pipeline shape mirrors the reference exactly: load CSV (native C++ parser)
-> transformers (MinMax pixel scaling, one-hot labels) -> trainer ->
predictor -> evaluator. Every accuracy printed here is measured against
real-world data the framework authors did not design.

Usage:
    python examples/real_digits.py [single|downpour|sync] [--cpu]
"""

from __future__ import annotations

import argparse
import sys
import time

sys.path.insert(0, ".")

from distkeras_tpu import (
    DOWNPOUR,
    AccuracyEvaluator,
    MinMaxTransformer,
    ModelPredictor,
    OneHotTransformer,
    SingleTrainer,
    SynchronousDistributedTrainer,
)
from distkeras_tpu.data.loaders import digits
from distkeras_tpu.models.zoo import digits_mlp


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("mode", nargs="?", default="single",
                    choices=["single", "downpour", "sync"])
    ap.add_argument("--epochs", type=int, default=10)
    ap.add_argument("--batch", type=int, default=32)
    ap.add_argument("--workers", type=int, default=4)
    ap.add_argument("--int8", action="store_true",
                    help="also evaluate an int8 weight-only serving copy "
                    "(ops.quantization.quantize_model) next to f32")
    ap.add_argument("--cpu", action="store_true",
                    help="force the CPU backend (virtual multi-device mesh)")
    args = ap.parse_args()
    from distkeras_tpu.parallel.backend import setup_backend

    # probe out-of-process: a dead TPU tunnel degrades to the virtual CPU
    # mesh instead of hanging in-process backend init (--cpu forces it)
    setup_backend(cpu=args.cpu, cpu_devices=max(args.workers, 8),
                  fallback_cpu_devices=max(args.workers, 8))

    # load real CSV -> scale 4-bit intensities to [0,1] -> one-hot labels
    raw = digits(flat=True)
    ds = MinMaxTransformer(n_min=0.0, n_max=1.0, o_min=0.0, o_max=16.0)(raw)
    ds = OneHotTransformer(10, input_col="label", output_col="label_onehot")(ds)
    train, test = ds.split(0.85, seed=0)
    print(f"real digits: {len(train)} train rows, {len(test)} test rows")

    if args.mode == "single":
        trainer = SingleTrainer(
            digits_mlp(seed=0), "adam", "categorical_crossentropy",
            learning_rate=1e-3, batch_size=args.batch,
            num_epoch=args.epochs, label_col="label_onehot", seed=0,
        )
    elif args.mode == "downpour":
        trainer = DOWNPOUR(
            digits_mlp(seed=0), "sgd", loss="categorical_crossentropy",
            learning_rate=0.08, batch_size=args.batch,
            num_epoch=args.epochs, num_workers=args.workers,
            communication_window=4, label_col="label_onehot",
            mode="threads", seed=0,
        )
    else:
        trainer = SynchronousDistributedTrainer(
            digits_mlp(seed=0), "sgd", "categorical_crossentropy",
            learning_rate=0.2, batch_size=max(args.batch // args.workers, 1),
            num_workers=args.workers, num_epoch=args.epochs,
            label_col="label_onehot", seed=0,
        )

    t0 = time.perf_counter()
    trained = trainer.train(train, shuffle=True)
    dt = time.perf_counter() - t0

    pred = ModelPredictor(trained, batch_size=256).predict(test)
    acc = AccuracyEvaluator(label_col="label").evaluate(pred)
    print(f"{args.mode}: {dt:.1f}s, REAL holdout accuracy {acc:.4f}")
    if args.int8:
        from distkeras_tpu.ops.quantization import quantize_model

        q = quantize_model(trained.copy())
        acc_q = AccuracyEvaluator(label_col="label").evaluate(
            ModelPredictor(q, batch_size=256).predict(test)
        )
        print(f"int8 serving copy: REAL holdout accuracy {acc_q:.4f} "
              f"(drop {acc - acc_q:+.4f})")


if __name__ == "__main__":
    main()
